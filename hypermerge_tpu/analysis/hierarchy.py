"""THE lock-hierarchy manifest — the machine-checked successor to the
"established lock order" comments that used to live scattered across
`backend/doc_backend.py`, `backend/repo_backend.py` and
`storage/integrity.py`.

Every lock in the package is created through
`analysis.lockdep.make_lock / make_rlock / make_condition` with a
**lock class** name declared here. Two checkers consume the manifest:

- the static linter (`analysis/linter.py`, run by `tools/lint.py` and
  `tests/test_analysis.py`): flags nested acquisitions that can invert
  the declared ranks, blocking calls inside no-block regions, and raw
  `threading.Lock()` creations that bypass the factory;
- the runtime lockdep (`analysis/lockdep.py`, `HM_LOCKDEP=1`): records
  the actual per-thread acquisition order, builds the global
  class-level lock-order graph, and reports *potential* cycles and
  held-across-blocking-call violations even when no deadlock fires.

Rank semantics: a thread may only acquire a lock whose rank is
STRICTLY GREATER than every ranked lock it already holds (re-entrant
re-acquisition of the same instance is exempt — several classes are
RLocks by design). `rank=None` classes are unranked: they still
participate in cycle detection, but no pairwise order is declared for
them (the net layer's fine-grained locks are ordered empirically by
the cycle detector rather than by decree). `leaf=True` means no other
tracked lock may be acquired while holding it. `no_block=True` marks
the GLOBAL coordination locks: no fsync / socket send / sqlite commit
/ thread join may run while they are held. Since the write-plane
split (backend/emission.py) the only no-block class is `live.engine`
— blocking under it would stall EVERY doc's tick coordination, and
`lock.held_blocking_ms.live_engine` must read zero at every HM_FSYNC
tier (the bench `config_lockdebt` gate). The per-doc emission domain
`doc.emit` is explicitly allowed to block: a durable ack (WAL group
commit, feed append) under it stalls exactly ONE doc.

The established core order (outermost first):

    repo.bulk -> doc.emit -> live.engine -> doc -> repo -> actor
              -> store.* -> util.* -> telemetry / util.debug

(`doc.emit` OUTRANKS the engine lock: an emission path holds its
doc's domain first and dips into the engine for table bookkeeping;
the tick looks docs up with a GIL-atomic snapshot and takes each
doc's domain with NO engine lock held — never two domains at once.)

with `store.integrity`, `telemetry.shard` and `util.debug` as leaves.
Leaf semantics are scoped to the RANKED world: a leaf may still touch
terminal unranked latches (the native-library load-once lock, the
fault recorders) — those are pure sinks and participate in cycle
detection only.
"""

from __future__ import annotations

import re
from typing import Dict, FrozenSet, NamedTuple, Optional, Tuple

# the dotted `subsystem.metric` telemetry naming convention — ONE
# definition shared by the static linter (analysis/linter.py) and the
# runtime creation-time assert (telemetry/registry.py under
# HM_LOCKDEP=1), so the two halves of the rule cannot drift
TELEMETRY_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z0-9_]+)+$")


class LockClass(NamedTuple):
    name: str
    rank: Optional[int]  # None = unranked (cycle detection only)
    doc: str
    leaf: bool = False
    no_block: bool = False


# ---------------------------------------------------------------------------
# the manifest

LOCK_CLASSES: Tuple[LockClass, ...] = (
    # -- ranked core (the documented hierarchy) -------------------------
    LockClass(
        "repo.bulk", 5,
        "RepoBackend._bulk_mutex — serializes whole bulk loads; held "
        "across ready-notifies that may take a doc's emission domain, "
        "so it is the outermost lock in the process.",
    ),
    LockClass(
        "doc.emit", 8,
        "DocBackend.emission (backend/emission.py EmissionDomain) — "
        "ONE re-entrant lock per doc, THE emission ordering domain: "
        "every {compute patch -> feed append -> push} pair of that "
        "doc (live ticks, apply_local echoes, Ready snapshots, the "
        "HM_LIVE=0 host path) holds exactly its own doc's domain. "
        "Cross-doc nesting is FORBIDDEN (a same-class edge is a "
        "lockdep order violation); a thread mid-emission that "
        "re-enters the repo for ANOTHER doc defers through "
        "emission.defer(). MAY block: a durable ack (WAL group "
        "commit, tier-2 fsync) under it stalls exactly one doc — "
        "that is the write-plane split.",
    ),
    LockClass(
        "live.engine", 10,
        "LiveApplyEngine._lock — tick/dirty-set COORDINATION only "
        "since the write-plane split: the doc table, "
        "refusal/adoption/demotion bookkeeping, and the LRU "
        "use-clock. Never held across a feed append, fsync, or "
        "frontend push (emissions run under the per-doc doc.emit "
        "domain, which OUTRANKS this lock); "
        "lock.held_blocking_ms.live_engine reading zero at every "
        "HM_FSYNC tier is the machine-checked invariant.",
        no_block=True,
    ),
    LockClass(
        "doc", 16,
        "DocBackend._lock — per-doc CRDT/lazy state. Ranks ABOVE the "
        "repo lock: the lazy replay (_ensure_opset / _replay_opset) "
        "holds it while its loader opens actors through the repo. The "
        "repo NEVER takes a doc lock while holding its own (DocBackend "
        "construction under the repo lock acquires nothing), and "
        "notifies always fire after the doc lock is released.",
    ),
    LockClass(
        "repo", 20,
        "RepoBackend._lock — docs/actors tables. Engine->repo is the "
        "established order (snapshots under the engine lock open "
        "actors under this one); repo->engine is the open()/Ready "
        "deadlock the PR-3 emission-lock unification removed.",
    ),
    LockClass(
        "actor", 35,
        "Actor._lock — per-feed change list + sidecar sync. Feed "
        "listeners fire outside the feed lock, so actor never nests "
        "inside store.feed.",
    ),
    LockClass(
        "repo.stats", 40,
        "RepoBackend._stats_lock — bulk-load stage timing "
        "accumulators (pipeline worker threads).",
    ),
    LockClass(
        "store.feed_store", 48,
        "FeedStore._lock — the feeds table; held while constructing "
        "Feeds, so it ranks above the per-feed locks' users but "
        "below the feed lock itself.",
    ),
    LockClass(
        "store.feed", 50,
        "Feed._lock — one append-only log. Held across storage "
        "append + merkle sign; listeners fire after release.",
    ),
    LockClass(
        "store.feed_io", 52,
        "FileFeedStorage._io — the cached write handles (log + .len "
        "sidecar) and every operation that uses or drops them: the "
        "appender (under store.feed) and the WAL checkpoint thread's "
        "storage.sync() share the SAME fds, so seek/write/fsync/close "
        "must serialize. Acquired under store.feed, holds across "
        "store.wal (the journal append rides inside a feed append).",
    ),
    LockClass(
        "store.colcache", 54,
        "FeedColumnCache._lock — per-feed columnar sidecar.",
    ),
    LockClass(
        "store.slab", 56,
        "CorpusSlab._lock — the repo's shared sidecar slab file.",
    ),
    LockClass(
        "store.sql", 60,
        "SqlDatabase._lock — statement + commit serialization. The "
        "sqlite commit itself runs under it by design; it is therefore "
        "the one store lock that may block, and nothing below it may "
        "be acquired while it is held except the fault recorder.",
    ),
    LockClass(
        "store.cursors", 62,
        "CursorStore._lock — the write-through cursor memory mirror. "
        "Ranks ABOVE store.sql: the write batches absorb into the "
        "mirror from inside db.bulk() (sql lock held), and hydration "
        "queries SQLite BEFORE taking the mirror lock "
        "(CursorStore._ensure_hydrated — the sql<->cursors AB/BA the "
        "first lockdep run caught).",
    ),
    LockClass(
        "store.durability", 66,
        "DurabilityManager._lock — the tier-1 dirty set. sync_now "
        "drains OUTSIDE it; mark_dirty is called under feed locks.",
    ),
    LockClass(
        "store.wal", 67,
        "WriteAheadLog._lock (storage/wal.py) — the shared per-repo "
        "journal: record appends and the group-commit handshake "
        "serialize under it (acquired under store.feed during a feed "
        "append, hence above it). The commit fsync itself runs "
        "OUTSIDE it — appenders keep writing while the leader "
        "syncs.",
    ),
    LockClass(
        "store.integrity", 70,
        "FeedIntegrity._lock — signed-merkle state. LEAF: proof "
        "serving and signing must not reach back into any other lock "
        "(the PR-1 integrity lock-order fix, now machine-checked).",
        leaf=True,
    ),
    LockClass(
        "serve.cache", 74,
        "serve.resident.ResidencyCache._lock — the HBM residency "
        "table (entries, LRU order, byte budget) plus the serve "
        "tier's host-side memo. Entry BUILDS (pack + kernel + device "
        "upload) run with NO serve lock held (the PR-4 "
        "install-and-recheck idiom); the critical sections are dict "
        "bookkeeping only, so nothing but the telemetry/debug leaves "
        "may be acquired under it. Ranks above the store locks: "
        "write-path emission hooks (engine lock held) mark entries "
        "stale under it.",
    ),
    LockClass(
        "serve.batch", 76,
        "serve.batcher.ReadBatcher._lock — admission-queue depth "
        "accounting. Held for counter arithmetic only; the debounced "
        "flush (util.debounce) is always marked OUTSIDE it.",
    ),
    LockClass(
        "serve.overload", 77,
        "serve.overload.OverloadController._lock — the brownout "
        "ladder's shared state: tenant token-bucket table, last "
        "signal sample, ticker lifecycle. Held for dict/arith "
        "bookkeeping only (telemetry shard installs nest inside); "
        "the hot-path state probe is a GIL-atomic read outside it.",
    ),
    LockClass(
        "util.debounce", 78,
        "Debouncer._lock/_cv — mark/flush handshake. flush_fn runs "
        "with NO debouncer lock held, so flushes may take any lock; "
        "mark() is called under store locks.",
    ),
    LockClass(
        "util.queue", 80,
        "utils.queue.Queue._lock — buffered handoff. Subscriber "
        "callbacks run outside it; only the debug lock nests inside "
        "(the subscribe log line).",
    ),
    LockClass(
        "telemetry.table", 90,
        "MetricsRegistry._lock — the series table. retire() folds a "
        "closed component's counters into an aggregate under it, "
        "installing a shard cell, so it ranks just above the shard "
        "locks and is NOT a leaf.",
    ),
    LockClass(
        "telemetry.shard", 92,
        "Counter/Gauge/Histogram shard-install locks. LEAF: a metric "
        "bump must be acquirable from under any lock in the process.",
        leaf=True,
    ),
    LockClass(
        "util.debug", 95,
        "utils.debug pattern/timing locks. LEAF: log() is called "
        "from under nearly every lock in the package.",
        leaf=True,
    ),
    # -- unranked (cycle detection only) --------------------------------
    LockClass(
        "live.gc", None,
        "backend.live._gc_pause_lock — GC pause refcount across "
        "adoption builds.",
    ),
    LockClass(
        "doc.emit.defer", None,
        "backend.emission deferred-emission worker — the cross-doc "
        "re-entry escape hatch: a thread holding doc A's emission "
        "domain that re-enters the repo for doc B parks the work "
        "here instead of nesting domains.",
    ),
    LockClass(
        "net.ipc.hub", None,
        "net.ipc._FrontendHub._lock — the multi-frontend daemon's "
        "connection/interest table (accept threads vs route).",
    ),
    LockClass(
        "net.ipc.router", None,
        "net.ipc._ShardRouter._lock — the HM_WORKERS write plane's "
        "worker-slot/pending/telemetry tables (route threads vs the "
        "respawn supervisor vs worker reader threads).",
    ),
    LockClass(
        "pipeline.err", None,
        "pipeline FetchContext._err_lock — first-error capture.",
    ),
    LockClass(
        "pipeline.pack_pool", None,
        "SlabPipeline._pack_cv — the pack pool's ordered-emit turn "
        "counter and EOF claim (HM_PACK_WORKERS workers race the pack "
        "queue but emit into the dispatch queue in slab order).",
    ),
    LockClass("front.repo", None, "RepoFrontend._lock."),
    LockClass("front.doc", None, "DocFrontend._lock."),
    LockClass(
        "ops.clock_mirror", None,
        "DeviceClockMirror._lock — host-buffered device clock table.",
    ),
    LockClass("native.load", None, "native library load-once latch."),
    LockClass("net.network", None, "Network._lock — peers table."),
    LockClass("net.swarm", None, "in-memory Swarm._lock."),
    LockClass(
        "net.peer", None,
        "NetworkPeer._plock — pending-connection list (accept/"
        "supervisor threads vs close-driven prunes).",
    ),
    LockClass(
        "net.conn", None,
        "PeerConnection._close_lock — close-listener registration "
        "atomic against the close snapshot.",
    ),
    LockClass("net.duplex", None, "in-memory Duplex._lock."),
    LockClass(
        "net.repl", None,
        "ReplicationManager._lock — per-peer cursor/want state.",
    ),
    LockClass(
        "net.sup", None,
        "SessionSupervisor._lock — outbound session table.",
    ),
    LockClass(
        "net.tcp", None,
        "TcpDuplex._lock — close/session state.",
    ),
    LockClass(
        "net.tcp.outbox", None,
        "TcpDuplex._out_cv — writer-thread outbox handoff.",
    ),
    LockClass(
        "net.tcp.server", None,
        "TcpSwarm._dlock — live duplex tracking.",
    ),
    LockClass(
        "net.tcp.accept", None,
        "TcpSwarm._accept_cv — the bounded inbound-handshake pool's "
        "queue handoff (accept thread vs pool workers). Held for "
        "deque bookkeeping only; handshakes run outside it.",
    ),
    LockClass(
        "net.aio", None,
        "aio.AioLoop._lock — the event loop's ready queue + timer "
        "heap (submitters from any thread vs the loop thread). Held "
        "for queue/heap bookkeeping only; callbacks and selector "
        "polling run outside it.",
    ),
    LockClass(
        "net.aio.conn", None,
        "aio.AioDuplex._lock — one async connection's outbox, close "
        "listeners and inbound-dispatch latch (senders from any "
        "thread vs the loop thread vs dispatch workers).",
    ),
    LockClass(
        "net.aio.dispatch", None,
        "aio.AioLoop._dispatch_cv — the bounded dispatch pool's "
        "queue handoff. User-facing callbacks run OUTSIDE it on the "
        "pool workers, never on the loop thread.",
    ),
    LockClass(
        "net.dht", None,
        "discovery.dht RoutingTable._lock — the k-bucket array + "
        "replacement caches. Pure table bookkeeping; liveness probes "
        "fire OUTSIDE it.",
    ),
    LockClass(
        "net.dht.store", None,
        "discovery.dht RecordStore._lock — the signed announce-record "
        "table (verification runs before the lock).",
    ),
    LockClass(
        "net.dht.rpc", None,
        "discovery.dht DhtNode._plock — the pending-RPC correlation "
        "table (reader thread vs timeout timers vs senders).",
    ),
    LockClass(
        "net.dht.swarm", None,
        "discovery.swarm DhtSwarm._lock — the joined-id and "
        "active-view target tables (join/leave callers vs the "
        "maintenance thread).",
    ),
    LockClass(
        "net.gossip", None,
        "discovery.gossip GossipSampler._lock — the per-key sample "
        "table. Held for dict bookkeeping only (the hot broadcast "
        "paths call sample()).",
    ),
    LockClass("net.fault.plan", None, "FaultPlan._lock — RNG streams."),
    LockClass(
        "net.fault.delay", None,
        "fault _DelayLine._cv — per-direction FIFO delay line.",
    ),
    LockClass("net.fault.swarm", None, "FaultSwarm._lock."),
    LockClass(
        "store.fault.plan", None, "DiskFaultPlan._lock — RNG streams.",
    ),
    LockClass(
        "store.fault.recorder", None,
        "CrashRecorder._lock — write/fsync/commit journal.",
    ),
    LockClass(
        "store.fault.active", None,
        "storage.faults._active_lock — plan activation latch.",
    ),
)

BY_NAME: Dict[str, LockClass] = {c.name: c for c in LOCK_CLASSES}
RANKED: Dict[str, int] = {
    c.name: c.rank for c in LOCK_CLASSES if c.rank is not None
}
LEAVES: FrozenSet[str] = frozenset(c.name for c in LOCK_CLASSES if c.leaf)
NO_BLOCK: FrozenSet[str] = frozenset(
    c.name for c in LOCK_CLASSES if c.no_block
)

# Lock-class pairs the cycle detector must NOT treat as ordered edges,
# each with a justification. Kept deliberately empty-by-default: a new
# entry is a reviewed decision, not a quick fix. (Format:
# ((holder_class, acquired_class), "why this nesting cannot deadlock").)
ALLOWED_EDGES: Dict[Tuple[str, str], str] = {}

# Methods that (transitively) acquire doc.emit / live.engine — the
# linter flags a call to any of these from inside a `with` holding a
# ranked lock whose rank is ABOVE the engine's (repo/doc/actor/store):
# that is exactly the repo->engine inversion the open()/Ready deadlock
# was made of, and since the write-plane split the same rule keeps a
# store/doc lock from being held into an emission domain acquisition.
# (`snapshot_patch` also enters the engine but shares its name with
# OpSet.snapshot_patch — a lexical linter cannot tell them apart, so
# the runtime lockdep detector owns that entrypoint.)
ENGINE_ENTRYPOINTS: FrozenSet[str] = frozenset(
    {"apply_local", "submit_remote", "demote_idle"}
)

# Attribute/function call names the no-blocking-under-lock rule treats
# as blocking primitives when they appear lexically inside a no_block
# `with` region. `.commit` is sqlite, `.sendall` the socket layer,
# `io_fsync`/`fsync` the durability seam, `.join`/`sleep`/`first`/
# `flush_now`/`barrier`/`sync_now` the wait-shaped calls.
BLOCKING_CALLS: FrozenSet[str] = frozenset(
    {
        "fsync", "io_fsync", "sendall", "commit", "join", "sleep",
        "first", "flush_now", "barrier", "sync_now", "wait",
    }
)


def rank_of(name: str) -> Optional[int]:
    """Declared rank for a lock class (None when unranked/unknown)."""
    return RANKED.get(name)


def validate() -> None:
    """Manifest self-check (run by tests): names unique, ranks unique
    among ranked classes, allowed-edge endpoints declared and
    justified."""
    names = [c.name for c in LOCK_CLASSES]
    if len(names) != len(set(names)):
        raise ValueError("duplicate lock class names in manifest")
    ranks = [c.rank for c in LOCK_CLASSES if c.rank is not None]
    if len(ranks) != len(set(ranks)):
        raise ValueError("duplicate ranks in manifest")
    for (a, b), why in ALLOWED_EDGES.items():
        if a not in BY_NAME or b not in BY_NAME:
            raise ValueError(f"allowed edge ({a}, {b}) names unknown class")
        if not why.strip():
            raise ValueError(f"allowed edge ({a}, {b}) lacks justification")
