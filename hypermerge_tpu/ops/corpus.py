"""On-disk benchmark corpus writer — valid repo state, written fast.

The cold-start benchmark (BASELINE configs 3/4: re-materialize 10k docs
x 1k ops from disk) needs a repo directory holding real product state:
per-actor block logs (storage/feed.py framing), columnar sidecars
(storage/colcache.py layout), and the sqlite rows (cursors/clocks/feeds)
a live repo would have persisted. Writing 10M ops through the
interactive `repo.change` path takes minutes of pure Python; this writer
produces byte-equivalent state directly:

- `distinct` template histories come from ops/synth.py `synth_changes`
  (single-writer chat-shaped docs, contiguous seqs 1..n);
- each template's change blocks and sidecar files are rendered once,
  then instantiated per doc by substituting the doc's actor id (the only
  per-doc content) and re-packing blocks;
- sqlite rows are written in one executemany per table.

Equivalence with the interactive write path is pinned by
tests/test_corpus.py: a corpus doc opens to exactly the state a repo
that executed the same changes persists.
"""

from __future__ import annotations

import os
import struct
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List

import numpy as np

from ..crdt.change import Change
from ..storage import block as blockmod
from ..storage.colcache import FeedColumnCache, MemoryColumnStorage
from ..storage.sql import SqlDatabase
from ..utils import keys as keymod
from ..utils.ids import to_doc_url
from ..utils.json_buffer import bufferify
from .synth import synth_changes

_HDR = struct.Struct("<I")  # storage/feed.py block framing
_TEMPLATE_ACTOR = "actor00"  # synth_changes' single-writer actor name
INFINITY_SEQ = 2**53 - 1  # crdt/clock.py INFINITY_SEQ


class _Template:
    """One synthetic history, pre-rendered for per-doc instantiation.

    The sidecar is one v3 checkpoint (storage/colcache.py): the planes,
    preds, and row-ends bytes are doc-invariant and rendered ONCE as
    `_body`; only the interner-tables blob names the writer actor, so
    per doc the checkpoint re-frames that blob around the shared body."""

    def __init__(self, changes: List[Change]) -> None:
        from ..storage.colcache import (
            planes_from_rows,
            v3_body_bytes,
            v3_frame,
        )

        self.n_changes = len(changes)
        self.raw_blocks = [bufferify(c.to_json()) for c in changes]
        cc = FeedColumnCache(
            MemoryColumnStorage(), writer=_TEMPLATE_ACTOR
        )
        for c in changes:
            cc.append_change(c)
        fc = cc.columns()
        planes = (
            fc.planes
            if fc.planes is not None
            else planes_from_rows(fc.ensure_rows())
        )
        row_ends = np.asarray(cc._commits_arr[:, 0], np.int64)
        flags = np.asarray(cc._commits_arr[:, 3], np.uint8)
        self._body = v3_body_bytes(planes, fc.preds, row_ends, flags)
        self._shape = (fc.n_rows, len(row_ends), len(fc.preds))
        self._tables = cc._tables_blob()
        self._frame = v3_frame

    def checkpoint_bytes(self, writer_pk: str) -> bytes:
        """The doc's sidecar: the shared checkpoint body framed with the
        writer actor substituted in the tables blob."""
        tables = self._tables.replace(
            _TEMPLATE_ACTOR.encode("ascii"), writer_pk.encode("ascii")
        )
        return self._frame(self._body, *self._shape, tables)


def _write_doc(
    feeds_root: str, pair: keymod.KeyPair, tpl: _Template, sign: bool,
    slab=None,
) -> None:
    from ..storage.integrity import sign_chain

    pk = pair.public_key
    d = os.path.join(feeds_root, pk[:2])
    os.makedirs(d, exist_ok=True)
    pkb = pk.encode("ascii")
    tab = _TEMPLATE_ACTOR.encode("ascii")
    # block log: template JSON with the doc's actor substituted, packed
    # through the product codec (storage/block.py); the .sig sidecar is
    # the same record chain a live writer persists (integrity.sign_chain
    # is the single source of truth for that format)
    blocks = [
        blockmod.pack_raw(raw.replace(tab, pkb)) for raw in tpl.raw_blocks
    ]
    parts: List[bytes] = []
    for b in blocks:
        parts.append(_HDR.pack(len(b)))
        parts.append(b)
    log_bytes = b"".join(parts)
    with open(os.path.join(d, pk), "wb") as fh:
        fh.write(log_bytes)
    # block-count index (storage/feed.py FileFeedStorage._LEN)
    with open(os.path.join(d, pk + ".len"), "wb") as fh:
        fh.write(struct.pack("<QQ", len(blocks), len(log_bytes)))
    if sign:
        with open(os.path.join(d, pk + ".sig"), "wb") as fh:
            fh.write(sign_chain(blocks, keymod.decode(pair.secret_key)))
    # columnar sidecar: one v3 checkpoint with this doc's writer
    # substituted in the tables blob (everything else is doc-invariant),
    # framed into the corpus slab (storage/slab.py) — or a per-feed
    # `.cols2` file when the slab layout is disabled
    ckpt = tpl.checkpoint_bytes(pk)
    if slab is not None:
        from ..storage.slab import KIND_IMAGE

        slab.append(KIND_IMAGE, pk, ckpt)
    else:
        with open(os.path.join(d, pk + ".cols2"), "wb") as fh:
            fh.write(ckpt)


def make_corpus(
    path: str,
    n_docs: int,
    n_ops: int,
    ops_per_change: int = 16,
    distinct: int = 8,
    seed: int = 0,
    threads: int = 8,
    sign: bool = True,
) -> List[str]:
    """Write a repo directory of `n_docs` single-writer docs with `n_ops`
    ops each; returns their doc urls. Safe to call once per directory.
    `sign=False` skips the .sig sidecars (faster; such feeds cannot
    replicate to strict peers)."""
    feeds_root = os.path.join(path, "feeds")
    os.makedirs(feeds_root, exist_ok=True)

    templates = [
        _Template(
            synth_changes(
                n_ops,
                n_actors=1,
                ops_per_change=ops_per_change,
                seed=seed + t,
            )
        )
        for t in range(min(distinct, n_docs))
    ]

    pairs = [keymod.create() for _ in range(n_docs)]

    slab = None
    if os.environ.get("HM_SLAB", "1") != "0":
        from ..storage.slab import CorpusSlab

        slab = CorpusSlab(os.path.join(feeds_root, "cols.slab"))
    try:
        with ThreadPoolExecutor(max_workers=threads) as pool:
            list(
                pool.map(
                    lambda i: _write_doc(
                        feeds_root,
                        pairs[i],
                        templates[i % len(templates)],
                        sign,
                        slab,
                    ),
                    range(n_docs),
                )
            )
    finally:
        if slab is not None:
            slab.close()

    db = SqlDatabase(os.path.join(path, "repo.db"))
    repo_pair = keymod.create()
    db.execute(
        "INSERT OR REPLACE INTO keys (name, public_key, secret_key) "
        "VALUES (?,?,?)",
        ("self.repo", repo_pair.public_key, repo_pair.secret_key),
    )
    rid = repo_pair.public_key
    with db.bulk():
        db.executemany(
            "INSERT OR REPLACE INTO cursors "
            "(repo_id, doc_id, actor_id, seq) VALUES (?,?,?,?)",
            [(rid, p.public_key, p.public_key, INFINITY_SEQ) for p in pairs],
        )
        db.executemany(
            "INSERT OR REPLACE INTO clocks "
            "(repo_id, doc_id, actor_id, seq) VALUES (?,?,?,?)",
            [
                (
                    rid,
                    p.public_key,
                    p.public_key,
                    templates[i % len(templates)].n_changes,
                )
                for i, p in enumerate(pairs)
            ],
        )
        db.executemany(
            "INSERT OR REPLACE INTO feeds "
            "(public_id, discovery_id, is_writable) VALUES (?,?,0)",
            [
                (p.public_key, keymod.discovery_id(p.public_key))
                for p in pairs
            ],
        )
    db.close()
    return [to_doc_url(p.public_key) for p in pairs]
