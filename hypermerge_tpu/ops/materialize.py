"""Bulk materialization: device kernel outputs -> patches / documents.

This is the cold-start path of the dual-path design (SURVEY.md §3.3: the
reference replays every change through Backend.applyChanges per doc; here
thousands of docs replay in one XLA dispatch via ops/crdt_kernels.py and
this module turns the winner/order/liveness lanes back into:

- `decode_patch`: a snapshot Patch identical in meaning to
  OpSet.snapshot_patch() — feeds DocReady messages to frontends.
- `materialize_docs`: plain Python document trees (equivalence-tested
  against the host OpSet path).
- `decode_columnar`: stays in numpy — the representation bulk consumers
  (bench, ClockStore-scale queries) should prefer; no per-entry Python
  objects.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..crdt.change import Action
from ..crdt.frontend_state import FrontendDoc
from ..crdt.patch import Conflict, Diff, Patch
from .columnar import ColumnarBatch, decode_value
from .crdt_kernels import MaterializeOut, run_batch

_OBJ_TYPES = {
    int(Action.MAKE_MAP): "map",
    int(Action.MAKE_LIST): "list",
    int(Action.MAKE_TEXT): "text",
    int(Action.MAKE_TABLE): "table",
}

ROOT_ROW = -1
ROOT_ID = "0@_root"


class DecodedBatch:
    """Numpy views of device outputs, shared by the decoders.

    Lanes transfer device->host lazily, on first attribute access — over
    the tunneled single-chip link each [D, N] lane costs ~100ms/MB, so a
    consumer that only needs clocks must not pay for ranks.
    """

    _LANES = (
        "visible", "map_winner", "elem_winner", "elem_live",
        "rank", "inc_total", "clock",
    )

    def __init__(
        self,
        batch: ColumnarBatch,
        out: MaterializeOut,
        host_clocks: Optional[List[Dict[str, int]]] = None,
    ) -> None:
        self.batch = batch
        self.cols = {k: np.asarray(v) for k, v in batch.cols.items()}
        self._out = out
        # authoritative per-doc clocks from the caller (lean kernel runs
        # don't transfer the seq wire, so the device clock lane is zeros)
        self.host_clocks = host_clocks

    def __getattr__(self, name: str):
        if name in DecodedBatch._LANES and "_out" in self.__dict__:
            arr = np.asarray(getattr(self._out, name))
            setattr(self, name, arr)
            if all(l in self.__dict__ for l in DecodedBatch._LANES):
                del self._out  # release the device buffers
            return arr
        raise AttributeError(name)

    def clock_dict(self, d: int) -> Dict[str, int]:
        if self.host_clocks is not None:
            return dict(self.host_clocks[d])
        return _local_clock_dict(
            self.batch, _doc_actors_row(self.batch, d), self.clock[d]
        )

    def doc_view(self, d: int) -> "DocView":
        """A one-doc view whose lanes transfer individually — opening a
        single doc out of a bulk batch must not pay for the whole [D, N]
        lane set (decode_patch accepts this in place of the batch)."""
        lanes = {}
        for name in DecodedBatch._LANES:
            if name in self.__dict__:
                lanes[name] = self.__dict__[name][d : d + 1]
            else:
                lanes[name] = np.asarray(getattr(self._out, name)[d])[
                    None
                ]
        cols = {k: v[d : d + 1] for k, v in self.cols.items()}
        return DocView(
            self.batch,
            cols,
            lanes,
            _doc_actors_row(self.batch, d),
            host_clock=(
                dict(self.host_clocks[d])
                if self.host_clocks is not None
                else None
            ),
        )


def _doc_actors_row(batch: ColumnarBatch, d: int) -> np.ndarray:
    from .crdt_kernels import ensure_doc_actors

    return ensure_doc_actors(batch)[d]


def _local_clock_dict(
    batch: ColumnarBatch, doc_actors: np.ndarray, clock_row: np.ndarray
) -> Dict[str, int]:
    """Decode a [A_loc] local-slot clock through the doc's actor map."""
    out: Dict[str, int] = {}
    for slot, gid in enumerate(np.asarray(doc_actors).ravel()):
        if gid < 0 or slot >= len(clock_row):
            continue
        s = int(clock_row[slot])
        if s > 0:
            out[batch.actors[int(gid)]] = s
    return out


class DocView:
    """One document's rows/lanes, shaped [1, N] — decode_patch(view, 0)."""

    def __init__(
        self, batch, cols, lanes, doc_actors, host_clock=None
    ) -> None:
        self.batch = batch
        self.cols = cols
        self.doc_actors = doc_actors
        self.host_clock = host_clock
        for name, arr in lanes.items():
            setattr(self, name, arr)

    def clock_dict(self, _d: int) -> Dict[str, int]:
        if self.host_clock is not None:
            return dict(self.host_clock)
        return _local_clock_dict(self.batch, self.doc_actors, self.clock[0])


def materialize_batch(
    docs_changes, n_rows: Optional[int] = None
) -> DecodedBatch:
    """Pack -> device kernel -> decoded views, in one call."""
    from .columnar import pack_docs

    batch = pack_docs(docs_changes, n_rows=n_rows)
    out = run_batch(batch)
    return DecodedBatch(batch, out)


# ---------------------------------------------------------------------------
# per-doc patch decode (runtime use: DocReady snapshots)


def decode_patch(dec: DecodedBatch, d: int) -> Patch:
    b, c = dec.batch, dec.cols
    action = c["action"][d]
    actor = c["actor"][d]
    ctr = c["ctr"][d]
    obj = c["obj"][d]
    key = c["key"][d]
    ref = c["ref"][d]
    insert = c["insert"][d]
    vkind = c["vkind"][d]
    value = c["value"][d]
    dt = c["dt"][d]
    visible = dec.visible[d]
    map_winner = dec.map_winner[d]
    elem_winner = dec.elem_winner[d]
    elem_live = dec.elem_live[d]
    rank = dec.rank[d]
    inc_total = dec.inc_total[d]

    def opid_str(row: int) -> str:
        return f"{int(ctr[row])}@{b.actors[int(actor[row])]}"

    def obj_id_str(row: int) -> str:
        return ROOT_ID if row == ROOT_ROW else opid_str(row)

    def row_value(row: int) -> Tuple[Any, bool, Optional[str]]:
        a = int(action[row])
        if a in _OBJ_TYPES:
            return opid_str(row), True, None
        v = decode_value(int(vkind[row]), int(value[row]), int(dt[row]), b)
        datatype = (
            "counter" if dt[row] == 1
            else "timestamp" if dt[row] == 2 else None
        )
        if datatype == "counter":
            v = (v or 0) + int(inc_total[row])
        return v, False, datatype

    # group winners/conflicts by container
    map_rows_by_obj: Dict[int, List[int]] = {}
    map_conf: Dict[Tuple[int, int], List[int]] = {}
    for r in np.nonzero(visible & (key >= 0))[0]:
        r = int(r)
        if map_winner[r]:
            map_rows_by_obj.setdefault(int(obj[r]), []).append(r)
        else:
            map_conf.setdefault((int(obj[r]), int(key[r])), []).append(r)

    # elements: live INS rows per container, ordered by descending rank
    elems_by_obj: Dict[int, List[int]] = {}
    for r in np.nonzero(elem_live)[0]:
        elems_by_obj.setdefault(int(obj[int(r)]), []).append(int(r))
    for rows in elems_by_obj.values():
        rows.sort(key=lambda r: -int(rank[r]))

    # winner value op per element + conflicts
    elem_val: Dict[int, int] = {}
    elem_conf: Dict[int, List[int]] = {}
    for r in np.nonzero(visible & (insert == 0) & (key < 0) & (ref >= 0))[0]:
        r = int(r)
        e = int(ref[r])
        if elem_winner[r]:
            elem_val[e] = r
        else:
            elem_conf.setdefault(e, []).append(r)
    for r in np.nonzero(elem_live & elem_winner)[0]:
        elem_val.setdefault(int(r), int(r))
    for r in np.nonzero(visible & (insert == 1))[0]:
        r = int(r)
        if elem_live[r] and not elem_winner[r]:
            elem_conf.setdefault(r, []).append(r)

    diffs: List[Diff] = []
    visited = set()

    def conflicts_for(rows: List[int]) -> tuple:
        # descending OpId = (ctr, actor-string) order, matching OpSet
        ordered = sorted(
            rows,
            key=lambda r: (int(ctr[r]), b.actors[int(actor[r])]),
            reverse=True,
        )
        out = []
        for r in ordered:
            v, link, datatype = row_value(r)
            out.append(Conflict(opid_str(r), v, link, datatype))
        return tuple(out)

    def emit_obj(row: int) -> None:
        if row in visited:
            return
        visited.add(row)
        oid = obj_id_str(row)
        otype = "map" if row == ROOT_ROW else _OBJ_TYPES[int(action[row])]
        if row != ROOT_ROW:
            diffs.append(Diff(action="create", obj=oid, obj_type=otype))
        if otype in ("list", "text"):
            for index, e in enumerate(elems_by_obj.get(row, [])):
                w = elem_val[e]
                v, link, datatype = row_value(w)
                if link:
                    emit_obj(w)
                diffs.append(
                    Diff(
                        action="insert",
                        obj=oid,
                        obj_type=otype,
                        index=index,
                        elem_id=opid_str(e),
                        value=v,
                        link=link,
                        datatype=datatype,
                        conflicts=conflicts_for(
                            [r for r in elem_conf.get(e, []) if r != w]
                        ),
                    )
                )
        else:
            rows = map_rows_by_obj.get(row, [])
            rows.sort(key=lambda r: b.keys[int(key[r])])
            for w in rows:
                v, link, datatype = row_value(w)
                if link:
                    emit_obj(w)
                diffs.append(
                    Diff(
                        action="set",
                        obj=oid,
                        obj_type=otype,
                        key=b.keys[int(key[w])],
                        value=v,
                        link=link,
                        datatype=datatype,
                        conflicts=conflicts_for(
                            map_conf.get((row, int(key[w])), [])
                        ),
                    )
                )

    emit_obj(ROOT_ROW)
    clock = dec.clock_dict(d)
    max_op = int(ctr.max(initial=0))
    return Patch(clock=clock, deps=clock, max_op=max_op, diffs=tuple(diffs))


def materialize_docs(dec: DecodedBatch) -> List[Any]:
    """Plain Python trees for every doc in the batch (test/equivalence
    path; bulk consumers should stay columnar via decode_columnar)."""
    out = []
    for d in range(dec.batch.n_docs):
        front = FrontendDoc()
        front.apply_patch(decode_patch(dec, d))
        out.append(front.materialize())
    return out


# ---------------------------------------------------------------------------
# columnar decode (bench / bulk path — no per-entry Python objects)


def decode_columnar(dec: DecodedBatch) -> Dict[str, np.ndarray]:
    """Vectorized summary of materialized state: winner masks, element
    order keys, clocks. This is the 'materialized' form bulk pipelines
    consume. Host reference path — bulk consumers should prefer
    `summarize_columnar`, which computes the same thing on device and
    transfers ~5x fewer bytes."""
    live_elems = dec.elem_live
    order_key = np.where(live_elems, -dec.rank, np.iinfo(np.int32).max)
    elem_order = np.argsort(order_key, axis=1, kind="stable")
    return {
        "map_winner": dec.map_winner,
        "elem_live": live_elems,
        "elem_order": elem_order,
        "n_live_elems": live_elems.sum(axis=1),
        "n_map_entries": dec.map_winner.sum(axis=1),
        "clock": dec.clock,
    }


def fetch_summary(wire, batch: ColumnarBatch, lean: bool = False):
    """Transfer + decode one slab's fused summary wire buffer (see
    ops/crdt_kernels.py summary_wire_spec for the byte layout)."""
    from .crdt_kernels import bucket_doc_actors, parse_summary_wire

    _da, A, _K = bucket_doc_actors(batch)
    return parse_summary_wire(
        np.asarray(wire), batch.n_rows, A, lean
    )


def summarize_columnar(batch: ColumnarBatch) -> Dict[str, np.ndarray]:
    """Bulk path: fused kernel+summary on device, ONE compact transfer,
    decode on host. Same keys/values as decode_columnar(run_batch(...))."""
    from .crdt_kernels import run_batch_summary

    return fetch_summary(run_batch_summary(batch), batch)


class BulkSummaries:
    """Host-side summaries of a bulk load's slabs — the product of the
    materialization barrier (RepoBackend.fetch_bulk_summaries). Slab
    arrays stay columnar (zero-copy for bulk consumers); `doc(id)` decodes
    one doc's counts + clock on demand.

    `memo_slabs` carries docs served from the backend's summary memo
    (clean docs whose clocks did not move since their last fetch — no
    pack, no dispatch, no transfer): (doc_ids, arrays, clock_dicts)
    groups whose arrays follow the same columnar contract, with the
    per-doc clock already decoded."""

    def __init__(self, pending, memo_slabs=None) -> None:
        # pending: (doc_ids, batch, dec, wire, lean) where wire is the
        # device summary buffer, None (host-kernel slab), or — when the
        # streaming pipeline's fetch worker already overlapped the
        # transfer+parse with later slabs' packs — the parsed arrays
        # dict itself
        self.slabs: List[Tuple[List[str], Optional[ColumnarBatch], Dict]] = []
        self._where: Dict[str, Tuple[int, int]] = {}
        for doc_ids, batch, dec, wire, lean in pending:
            if wire is None:  # host-kernel slab: no device refs
                arrays = decode_columnar(dec)
            elif isinstance(wire, dict):  # pre-fetched by the pipeline
                arrays = wire
            else:
                arrays = fetch_summary(wire, batch, lean)
            if dec.host_clocks is not None:
                # lean slabs never transferred the seq wire (nor the
                # wire's clock section), so the clock lane is zeros:
                # rebuild it from the authoritative host clocks so the
                # columnar contract (arrays()['clock']) stays consistent
                # with doc()
                from .crdt_kernels import ensure_doc_actors

                da = ensure_doc_actors(batch)
                clock = np.array(arrays["clock"])  # device fetches are
                # read-only buffers; mutate a copy
                for j, hc in enumerate(dec.host_clocks):
                    if not hc:
                        continue
                    for slot, gid in enumerate(da[j]):
                        if gid >= 0:
                            clock[j, slot] = hc.get(
                                batch.actors[int(gid)], 0
                            )
                arrays["clock"] = clock
            self._add_slab(doc_ids, batch, arrays)
        for doc_ids, arrays, clock_dicts in memo_slabs or ():
            arrays = dict(arrays)
            arrays["clock_dicts"] = list(clock_dicts)
            self._add_slab(doc_ids, None, arrays)

    def _add_slab(self, doc_ids, batch, arrays) -> None:
        # only small per-doc dicts are retained — the DecodedBatch
        # (device lanes + column copies) must be releasable once docs
        # drop their lazy snapshot closures
        self.slabs.append((doc_ids, batch, arrays))
        for j, d in enumerate(doc_ids):
            self._where[d] = (len(self.slabs) - 1, j)

    @property
    def doc_ids(self) -> List[str]:
        return list(self._where.keys())

    def arrays(self, doc_id: str) -> Tuple[Dict, int]:
        """(slab arrays, row index) holding this doc."""
        si, j = self._where[doc_id]
        return self.slabs[si][2], j

    def doc(self, doc_id: str) -> Dict[str, Any]:
        si, j = self._where[doc_id]
        doc_ids, batch, arrays = self.slabs[si]
        if batch is None:  # memo-served group: clock pre-decoded
            clock = dict(arrays["clock_dicts"][j])
        else:
            clock = _local_clock_dict(
                batch, _doc_actors_row(batch, j), arrays["clock"][j]
            )
        return {
            "elems": int(arrays["n_live_elems"][j]),
            "map_entries": int(arrays["n_map_entries"][j]),
            "clock": clock,
        }


def text_join(dec: DecodedBatch, d: int, text_obj_row: int) -> str:
    """Fast text materialization: join the winner chars of one text object
    in RGA order (numpy sort, no per-char Python)."""
    c = dec.cols
    mask = (
        dec.elem_live[d]
        & (c["obj"][d] == text_obj_row)
        & (c["insert"][d] == 1)
    )
    rows = np.nonzero(mask)[0]
    rows = rows[np.argsort(-dec.rank[d][rows], kind="stable")]
    strings = dec.batch.strings
    # pull the selected columns to host ONCE — per-element indexing of
    # a (possibly device-resident) array is a scalar transfer each on
    # the TPU tunnel, which at automerge-perf scale (260k chars) costs
    # more than the whole kernel
    vals = np.asarray(c["value"][d])[rows].tolist()
    kinds = np.asarray(c["vkind"][d])[rows].tolist()
    return "".join(
        strings[v] if k == 3 else "" for v, k in zip(vals, kinds)
    )
