"""On-device pack kernel: the cold open's last host compute as a jitted
prefix-scatter (HM_DEVICE_PACK=1).

Rung 2 of the parallel pack plane (rung 1 is the HM_PACK_WORKERS thread
pool in backend/pipeline.py). Instead of scattering the padded [Dp, N]
column planes on host — hm_pack_prefix in C++, or the numpy twin — the
host only CONCATENATES the raw narrow feed planes into [M] int32
vectors (memcpy-bound, so the host pack stage is O(IO)), uploads them,
and ONE jitted program derives every wire column (obj/ref row
resolution, key/value global LUT remaps, writer broadcast) and scatters
it into the padded planes on device. Programs live in the PR-7 shared
program table under ("pack", Mp, Dp, N, row_dt, kdt, lut-lens) keys;
every axis buckets to pow2, so a corpus sweep reuses a handful of
executables and sharded.trace_counts pins the one-trace contract.

Placement rides the mesh: the bulk loader passes the chip strict
round-robin will dispatch the slab to (SlabRoundRobin.pack_device_for),
so the packed columns are born on the chip that materializes them.

Bit-identity contract: the planes returned are byte-equal to the host
twins' _pack_wire_dtypes output (the fuzz matrix in
tests/test_native_pack.py pins numpy == native == device). Pad rows
scatter into a scratch slot (index Dp*N of a Dp*N+1 flat buffer, sliced
off) and carry value 0 / vkind VK_NONE, so the device value min/max
over the padded [Mp] vector matches the host twins' min(initial=0) /
max(initial=0) and the value plane's int16-vs-int32 wire decision is
identical. LUT gathers clamp to the padded table like the numpy twin
clamps to the real one — out-of-range lanes are discarded by the same
where() masks, so the clamp bound never reaches the output.

Anything the kernel can't serve — no jax, no device, a tracing failure
— returns {} and the caller (ops/columnar._try_pack_prefix_single)
falls through native -> numpy, so HM_DEVICE_PACK=1 on a host-only box
degrades to exactly today's path; fallbacks are a counter, never an
error (telemetry pack.device_fallbacks).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import numpy as np

from .. import telemetry
from ..utils.debug import log

_M_PACKS = telemetry.counter("pack.device_packs")
_M_FALLBACKS = telemetry.counter("pack.device_fallbacks")

# source plane order the kernel consumes (matches the native entry's
# _PACK_SRC_PLANES so the marshalling loop is the same shape)
_SRC_PLANES = (
    "action", "ctr", "seq", "obj_ctr", "obj_a", "key",
    "ref_ctr", "ref_a", "insert", "vkind", "value", "dt",
)


def device_pack_enabled() -> bool:
    """HM_DEVICE_PACK=1 opts the fast pack path onto the device kernel.
    Default off: the host native pack is faster below the transfer
    break-even and is always available."""
    return os.environ.get("HM_DEVICE_PACK", "0") == "1"


def _round_up_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def _build_pack(Mp, Dp, N, row16, key16, PAD, OBJ_ROOT, REF_HEAD,
                REF_NONE, VK_STR, VK_FLOAT, VK_BIGINT, COLUMNS):
    """The traced pack program for one shape bucket. Every operand is a
    trace constant except the [Mp] planes and pow2-padded LUTs."""
    import jax.numpy as jnp

    rdt = jnp.int16 if row16 else jnp.int32
    kt = jnp.int16 if key16 else jnp.int32
    L = Dp * N + 1  # +1 scratch slot: pad rows land there, sliced off
    defaults = {"action": PAD, "obj": -1, "key": -1, "ref": REF_NONE}
    out_dt = {
        "action": jnp.uint8, "insert": jnp.uint8, "vkind": jnp.uint8,
        "dt": jnp.uint8, "actor": jnp.int32, "ctr": rdt, "seq": rdt,
        "obj": rdt, "key": kt, "ref": rdt, "value": jnp.int32,
    }

    def fn(action, ctr, seq, obj_ctr, obj_a, key, ref_ctr, ref_a,
           insert, vkind, value, dt, flat_idx, actor_rows,
           koff, soff, foff, boff, klut, slut, flut, blut):
        # -- derived columns, in wire dtypes (cast-then-subtract so the
        # int16 arithmetic matches the numpy twin bit for bit) ---------
        obj_row = jnp.where(
            obj_a == 0, obj_ctr.astype(rdt) - 1, rdt(OBJ_ROOT)
        )
        ref_row = jnp.where(
            ref_a == 0,
            ref_ctr.astype(rdt) - 1,
            jnp.where(ref_a == -2, rdt(REF_HEAD), rdt(REF_NONE)),
        )
        kidx = jnp.clip(koff + key, 0, klut.shape[0] - 1)
        key_g = jnp.where(key >= 0, klut[kidx].astype(kt), kt(-1))
        value_g = value
        for code, lut, off in (
            (VK_STR, slut, soff),
            (VK_FLOAT, flut, foff),
            (VK_BIGINT, blut, boff),
        ):
            idx = jnp.clip(off + value, 0, lut.shape[0] - 1)
            value_g = jnp.where(vkind == code, lut[idx], value_g)
        # pad rows carry value 0 / vkind VK_NONE, so folding 0 in makes
        # the reduction equal the host twins' min(initial=0) even when
        # M == Mp (no pad rows at all)
        vmin = jnp.minimum(value_g.min(), 0).astype(jnp.int32)
        vmax = jnp.maximum(value_g.max(), 0).astype(jnp.int32)

        sources = {
            "action": action, "actor": actor_rows, "ctr": ctr,
            "seq": seq, "obj": obj_row, "key": key_g, "ref": ref_row,
            "insert": insert, "vkind": vkind, "value": value_g,
            "dt": dt,
        }
        outs = []
        for name in COLUMNS:
            dtv = out_dt[name]
            flat = jnp.full(L, defaults.get(name, 0), dtv)
            flat = flat.at[flat_idx].set(sources[name].astype(dtv))
            outs.append(flat[: L - 1].reshape(Dp, N))
        return tuple(outs) + (vmin, vmax)

    return fn


def _pack_program(Mp, Dp, N, row16, key16, lut_lens):
    import jax

    from ..parallel import sharded
    from ..storage.colcache import (
        OBJ_ROOT, REF_HEAD, REF_NONE, VK_BIGINT, VK_FLOAT, VK_STR,
    )
    from .columnar import COLUMNS, PAD

    key = ("pack", Mp, Dp, N, row16, key16) + lut_lens
    return sharded._program(
        key,
        lambda: jax.jit(
            sharded._traced(
                key,
                _build_pack(
                    Mp, Dp, N, row16, key16, PAD, OBJ_ROOT, REF_HEAD,
                    REF_NONE, VK_STR, VK_FLOAT, VK_BIGINT, COLUMNS,
                ),
            )
        ),
    )


def _m_vec(a, Mp, fill=0) -> np.ndarray:
    """[M] -> [Mp] int32, pow2-padded with `fill`."""
    out = np.full(Mp, fill, np.int32)
    out[: len(a)] = a
    return out


def _lut_vec(a) -> np.ndarray:
    """Flat LUT -> pow2-padded int32 (global interner ids fit int32)."""
    n = _round_up_pow2(max(len(a), 1))
    out = np.zeros(n, np.int32)
    out[: len(a)] = a
    return out


def device_pack_prefix(
    fcs, fc_idx, fc_idx_a, ends, writer_g, flat_lut,
    D, Dp, N, i16ok, row_dt, kdt, device=None,
) -> Dict[str, np.ndarray]:
    """Device twin of columnar._native_pack_prefix: same operands, same
    {} -> fall-through contract, planes byte-identical to the host
    twins. The host side is pure marshalling — narrow-plane concats and
    int32 casts into [Mp] vectors — and the scatter/remap compute rides
    the jitted program (on `device` when the mesh scheduler predicted
    the slab's chip, the default device otherwise)."""
    if not device_pack_enabled():
        return {}
    try:
        import jax
    except Exception:
        return {}
    from ..storage.colcache import PLANE_NAMES

    try:
        # -- marshal [M] source vectors (the only host compute) --------
        use_planes = all(fc.planes is not None for fc in fcs)
        if use_planes:
            def col(name):
                return np.concatenate(
                    [
                        fcs[fc_idx[d]].plane(name)[: ends[d]]
                        for d in range(D)
                    ]
                )
        else:
            R = np.concatenate(
                [
                    fcs[fc_idx[d]].ensure_rows()[: ends[d]]
                    for d in range(D)
                ],
                axis=0,
            )

            def col(name):
                return R[:, PLANE_NAMES.index(name)]

        # the same corrupt-sidecar guard the native entry applies
        feed_rows = np.asarray([fc.n_rows for fc in fcs], np.int64)
        if np.any(ends > feed_rows[fc_idx_a]):
            return {}

        M = int(ends.sum())
        Mp = _round_up_pow2(max(M, 1))
        doc_col = np.repeat(np.arange(D, dtype=np.int64), ends)
        doc_starts = np.zeros(D + 1, np.int64)
        np.cumsum(ends, out=doc_starts[1:])
        pos = np.arange(M, dtype=np.int64) - doc_starts[doc_col]
        # pad rows scatter into the program's scratch slot Dp*N
        flat_idx = _m_vec(doc_col * N + pos, Mp, fill=Dp * N)

        planes = [_m_vec(col(n), Mp) for n in _SRC_PLANES]
        actor_rows = _m_vec(np.repeat(writer_g[fc_idx_a], ends), Mp)
        klut, koffs = flat_lut("k")
        slut, soffs = flat_lut("s")
        flut, foffs = flat_lut("f")
        blut, boffs = flat_lut("b")
        offs_rows = [
            _m_vec(np.repeat(o[fc_idx_a], ends), Mp)
            for o in (koffs, soffs, foffs, boffs)
        ]
        luts = [_lut_vec(t) for t in (klut, slut, flut, blut)]

        fn = _pack_program(
            Mp, Dp, N, bool(i16ok), kdt == np.int16,
            tuple(t.shape[0] for t in luts),
        )
        args = planes + [flat_idx, actor_rows] + offs_rows + luts
        if device is not None:
            args = [jax.device_put(a, device) for a in args]
        out = fn(*args)

        # -- back to host wire planes (value dtype decided by minmax) --
        from .columnar import COLUMNS, _pack_wire_dtypes

        vmin, vmax = int(out[-2]), int(out[-1])
        dtypes = _pack_wire_dtypes(i16ok, row_dt, kdt, vmin, vmax)
        cols: Dict[str, np.ndarray] = {}
        for ci, name in enumerate(COLUMNS):
            arr = np.asarray(out[ci])
            if arr.dtype != np.dtype(dtypes[name]):
                arr = arr.astype(dtypes[name])
            cols[name] = arr
        _M_PACKS.add(1)
        return cols
    except Exception as e:  # degrade, never fail the load
        _M_FALLBACKS.add(1)
        log("ops:pack", f"device pack fell back to host: {e}")
        return {}
