"""Batched JAX/XLA kernels: the TPU compute path of the framework."""
