"""Speculative XLA compile warmup for the bulk cold-start path.

On the tunneled TPU backend every distinct executable costs tens of
seconds of *remote* compile the first time a process dispatches it —
but the compile runs on the far side of the tunnel, leaving ~93% of the
single host core free. A deployment that knows it is about to bulk-open
a corpus (a server starting up, the benchmark writing its corpus) can
therefore hide the entire compile behind its own host-side IO by
starting warmup in a daemon thread first.

The warmup compiles the *exact* executables `RepoBackend.open_many`
will dispatch: it packs the same synthetic single-writer template
histories the benchmark corpus is built from (ops/corpus.py `distinct`
templates via ops/synth.py), padded to the same slab buckets, through
the same `run_batch_full` entry — so dtypes, A_loc/K buckets, and pred
widths all land on the jit cache key the real load produces. If a real
load's shapes differ, the warmup was merely an extra cached executable;
correctness is untouched (jit keys on shapes).

Parity note: the reference has no equivalent — Node JITs nothing ahead
of time. This is TPU-native infrastructure in the same spirit as the
persistent compilation cache (ops/crdt_kernels.py), which handles the
second process; warmup handles the first.
"""

from __future__ import annotations

import math
import os
import threading
from typing import List, Optional

INF = float("inf")


def bulk_buckets(n_docs_total: int, slab: Optional[int] = None) -> List[int]:
    """The doc-axis jit buckets `_load_slabs` will use for a bulk load of
    `n_docs_total` docs: full slabs share one bucket, the tail rounds up
    to its own pow2 (backend/repo_backend.py:_load_slabs)."""
    from .columnar import round_up_pow2

    if slab is None:
        slab = int(os.environ.get("HM_BULK_SLAB", "4096"))
    buckets = []
    for base in range(0, n_docs_total, slab):
        chunk = min(slab, n_docs_total - base)
        b = round_up_pow2(chunk)
        if b not in buckets:
            buckets.append(b)
    return buckets


def _warm(
    n_docs_total: int,
    n_ops: int,
    slab: Optional[int],
    ops_per_change: int,
    distinct: int,
    seed: int,
) -> None:
    import numpy as np

    from ..crdt.change import Action
    from ..storage.colcache import FeedColumnCache, MemoryColumnStorage
    from .columnar import pack_docs_columns, round_up_pow2
    from .crdt_kernels import run_batch_full
    from .synth import synth_changes

    min_cells = int(os.environ.get("HM_DEVICE_MIN_CELLS", "131072"))
    n_rows = round_up_pow2(max(1, n_ops))

    # the corpus' own template histories (ops/corpus.py make_corpus
    # defaults) -> identical value ranges, pred widths, and key tables
    specs = []
    for t in range(max(1, distinct)):
        # "actor00" is synth_changes' single-writer actor name — the
        # cache writer must match or refs look foreign and packing falls
        # off the no-sort fast path (ops/corpus.py _TEMPLATE_ACTOR)
        cc = FeedColumnCache(MemoryColumnStorage(), writer="actor00")
        for c in synth_changes(
            n_ops, n_actors=1, ops_per_change=ops_per_change, seed=seed + t
        ):
            cc.append_change(c)
        specs.append([(cc.columns(), 0, INF)])

    for bucket in bulk_buckets(n_docs_total, slab):
        if bucket * n_rows < min_cells:
            continue  # host-kernel path: nothing to compile
        batch = pack_docs_columns(
            specs[: min(len(specs), bucket)], n_docs=bucket, n_rows=n_rows
        )
        lean = not bool(np.any(batch.cols["action"] == int(Action.INC)))
        out, summary = run_batch_full(batch, lean=lean)
        # force compile completion (dispatch alone returns early)
        np.asarray(summary.ravel()[:1])


def warmup_bulk(
    n_docs_total: int,
    n_ops: int,
    slab: Optional[int] = None,
    ops_per_change: int = 16,
    distinct: int = 8,
    seed: int = 0,
    background: bool = True,
) -> Optional[threading.Thread]:
    """Compile the bulk-load executables for a `n_docs_total` x `n_ops`
    corpus ahead of the load. `background=True` returns a started daemon
    thread (callers need not join: a real load issued meanwhile simply
    blocks inside jit until the shared executable is ready);
    `background=False` compiles inline and returns None."""
    if background:
        th = threading.Thread(
            target=_warm,
            args=(n_docs_total, n_ops, slab, ops_per_change, distinct, seed),
            daemon=True,
            name="hm-warmup",
        )
        th.start()
        return th
    _warm(n_docs_total, n_ops, slab, ops_per_change, distinct, seed)
    return None
