"""DeviceClockMirror — the ClockStore's device-resident query twin.

The reference answers bulk clock queries by scanning sqlite rows per
call (reference src/ClockStore.ts:63-72 getMultiple + Clock.ts folds).
The TPU-first shape keeps the whole [docs, actors] clock matrix
RESIDENT in device HBM and applies writes as small batched scatter-max
updates, so the hot bulk queries — union across all docs, domination
against a cursor, top-k covered docs — are single dispatches that read
nothing from the host beyond the query vector:

- writes buffer host-side (dict of (row, col) -> seq, monotonic max)
  and flush lazily as ONE scatter-max right before the next query —
  interactive writes never pay a device round trip;
- capacity grows by pow2 doubling on either axis (device-side pad);
  jit buckets stay stable per capacity;
- seqs clamp to INT32_INF like the rest of the clock kernels.

`ClockStore.attach_mirror` keeps a mirror consistent with every sqlite
write (update/update_many/set/delete_doc), which the consistency test
pins against the raw rows (tests/test_clock_mirror.py).
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Optional, Tuple

from ..analysis.lockdep import make_rlock

import numpy as np

INT32_INF = 2**31 - 1


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _lazy_jits():
    """Module-level jitted programs, built on first use (importing jax
    at module import would drag device init into cold paths)."""
    global _scatter_max, _scatter_max_union
    import jax
    import jax.numpy as jnp

    @jax.jit
    def _scatter_max(m, r, c, v):
        return m.at[r, c].max(v)

    @jax.jit
    def _scatter_max_union(m, r, c, v):
        m2 = m.at[r, c].max(v)
        return m2, jnp.max(m2, axis=0)

    return _scatter_max, _scatter_max_union


_scatter_max = None
_scatter_max_union = None


def _jits():
    if _scatter_max is None:
        _lazy_jits()
    return _scatter_max, _scatter_max_union


class DeviceClockMirror:
    def __init__(
        self, capacity_docs: int = 1024, capacity_actors: int = 64
    ) -> None:
        self._lock = make_rlock("ops.clock_mirror")
        self.doc_index: Dict[str, int] = {}
        self.actor_index: Dict[str, int] = {}
        self._actors: List[str] = []
        self._docs: List[str] = []
        self._cap_d = _pow2(max(1, capacity_docs))
        self._cap_a = _pow2(max(1, capacity_actors))
        # device state is LAZY: writes only buffer host-side, so a repo
        # can attach a mirror unconditionally without paying device init
        # (or any dispatch) until the first bulk query
        self._matrix = None
        self._pending: Dict[Tuple[int, int], int] = {}

    @property
    def _jnp(self):
        import jax.numpy as jnp

        return jnp

    def _mat(self):
        if self._matrix is None:
            self._matrix = self._jnp.zeros(
                (self._cap_d, self._cap_a), self._jnp.int32
            )
        return self._matrix

    # -- host-side indexing --------------------------------------------

    def _doc_row(self, doc_id: str) -> int:
        row = self.doc_index.get(doc_id)
        if row is None:
            row = len(self._docs)
            self.doc_index[doc_id] = row
            self._docs.append(doc_id)
            if row >= self._cap_d:
                self._grow(docs=True)
        return row

    def _actor_col(self, actor_id: str) -> int:
        col = self.actor_index.get(actor_id)
        if col is None:
            col = len(self._actors)
            self.actor_index[actor_id] = col
            self._actors.append(actor_id)
            if col >= self._cap_a:
                self._grow(docs=False)
        return col

    def _grow(self, docs: bool) -> None:
        if docs:
            self._cap_d *= 2
        else:
            self._cap_a *= 2
        if self._matrix is not None:
            pad = (
                (0, self._cap_d - self._matrix.shape[0]),
                (0, self._cap_a - self._matrix.shape[1]),
            )
            self._matrix = self._jnp.pad(self._matrix, pad)

    # -- writes ---------------------------------------------------------

    def seed_bulk(self, doc_ids, actor_ids, matrix) -> None:
        """Bulk initialization from a dense [docs, actors] array: one
        device upload, capacity-padded. Only valid on an empty mirror
        (attach-time seeding, benchmarks)."""
        with self._lock:
            if self.doc_index or self.actor_index or self._pending:
                raise RuntimeError("seed_bulk on a non-empty mirror")
            self._docs = list(doc_ids)
            self._actors = list(actor_ids)
            self.doc_index = {d: i for i, d in enumerate(self._docs)}
            self.actor_index = {a: i for i, a in enumerate(self._actors)}
            self._cap_d = max(self._cap_d, _pow2(max(1, len(self._docs))))
            self._cap_a = max(
                self._cap_a, _pow2(max(1, len(self._actors)))
            )
            arr = np.asarray(matrix)
            assert arr.shape == (len(self._docs), len(self._actors))
            padded = np.zeros((self._cap_d, self._cap_a), np.int32)
            padded[: arr.shape[0], : arr.shape[1]] = np.minimum(
                arr, INT32_INF
            )
            self._matrix = self._jnp.asarray(padded)

    def update(self, doc_id: str, clock: Dict[str, int]) -> None:
        """Monotonic merge (max) — buffered; flushed at next query."""
        with self._lock:
            row = self._doc_row(doc_id)
            for actor, seq in clock.items():
                key = (row, self._actor_col(actor))
                s = min(int(seq), INT32_INF)
                if s > self._pending.get(key, 0):
                    self._pending[key] = s

    def update_many(self, clocks: Dict[str, Dict[str, int]]) -> None:
        for doc_id, clock in clocks.items():
            self.update(doc_id, clock)

    def set(self, doc_id: str, clock: Dict[str, int]) -> None:
        """Hard overwrite of one doc's row (ClockStore.set)."""
        jnp = self._jnp
        with self._lock:
            self._flush_locked()
            row = self._doc_row(doc_id)
            # resolve columns first: _actor_col may grow the matrix
            pairs = [
                (self._actor_col(a), min(int(s), INT32_INF))
                for a, s in clock.items()
            ]
            vec = np.zeros(self._cap_a, np.int32)
            for col, s in pairs:
                vec[col] = s
            self._matrix = self._mat().at[row].set(jnp.asarray(vec))

    def delete_doc(self, doc_id: str) -> None:
        with self._lock:
            row = self.doc_index.get(doc_id)
            if row is None:
                return
            self._flush_locked()
            self._matrix = self._mat().at[row].set(0)
            # row index stays allocated (zeros = neutral for max/union;
            # dominated() masks unallocated/deleted rows by doc list)
            del self.doc_index[doc_id]
            self._docs[row] = None

    # -- flush ----------------------------------------------------------

    def _pending_arrays(self):
        """Pending writes as (rows, cols, vals) padded to a pow2 bucket
        (stable jit shapes); the pad is a scatter-max of 0 at (0, 0) —
        a no-op against the non-negative matrix."""
        items = self._pending
        self._pending = {}
        n = len(items)
        cap = _pow2(max(1, n))
        rows = np.zeros(cap, np.int32)
        cols = np.zeros(cap, np.int32)
        vals = np.zeros(cap, np.int32)
        rows[:n] = np.fromiter((k[0] for k in items), np.int32, count=n)
        cols[:n] = np.fromiter((k[1] for k in items), np.int32, count=n)
        vals[:n] = np.fromiter(items.values(), np.int32, count=n)
        return rows, cols, vals

    def _flush_locked(self) -> None:
        if not self._pending:
            return
        jnp = self._jnp
        rows, cols, vals = self._pending_arrays()
        scatter, _ = _jits()
        self._matrix = scatter(
            self._mat(), jnp.asarray(rows), jnp.asarray(cols),
            jnp.asarray(vals),
        )

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    # -- queries (single dispatches over the resident matrix) ----------

    def union(self) -> Dict[str, int]:
        """Union clock across ALL docs — one device dispatch, even with
        writes pending (the scatter-max flush and the max-reduce fuse
        into a single program; over a tunneled device every round trip
        is ~100ms of wall clock)."""
        from . import clock_kernels as K

        with self._lock:
            if self._pending:
                jnp = self._jnp
                rows, cols, vals = self._pending_arrays()
                _, scatter_union = _jits()
                self._matrix, merged = scatter_union(
                    self._mat(), jnp.asarray(rows), jnp.asarray(cols),
                    jnp.asarray(vals),
                )
                merged = np.asarray(merged)
            else:
                merged = np.asarray(K.union_reduce(self._mat()))
            return {
                a: int(merged[c])
                for a, c in self.actor_index.items()
                if merged[c] > 0
            }

    def dominated(self, query: Dict[str, int]) -> List[str]:
        """Doc ids whose clock the query dominates (is >= everywhere)."""
        with self._lock:
            self._flush_locked()
            q = self._query_vec(query)
            ok = np.asarray(
                self._jnp.all(self._mat() <= q[None, :], axis=-1)
            )
            return [
                d for d, r in self.doc_index.items() if ok[r]
            ]

    def top_k_dominated(
        self, query: Dict[str, int], k: int
    ) -> List[str]:
        from . import clock_kernels as K

        with self._lock:
            self._flush_locked()
            q = self._query_vec(query)
            scores, idx = K.top_k_dominated(self._mat(), q, k)
            scores = np.asarray(scores)
            idx = np.asarray(idx)
            out = []
            for s, i in zip(scores, idx):
                if s < 0:
                    break
                d = self._docs[int(i)] if int(i) < len(self._docs) else None
                if d is not None:
                    out.append(d)
            return out

    def _query_vec(self, query: Dict[str, int]):
        jnp = self._jnp
        q = np.zeros(self._cap_a, np.int32)
        for actor, seq in query.items():
            col = self.actor_index.get(actor)
            if col is not None:
                q[col] = min(int(seq), INT32_INF)
        return jnp.asarray(q)

    # -- introspection ---------------------------------------------------

    def rows(self) -> Dict[str, Dict[str, int]]:
        """Full host decode (consistency tests; not a hot path)."""
        with self._lock:
            self._flush_locked()
            m = np.asarray(self._mat())
            return {
                d: {
                    a: int(m[r, c])
                    for a, c in self.actor_index.items()
                    if m[r, c] > 0
                }
                for d, r in self.doc_index.items()
            }
