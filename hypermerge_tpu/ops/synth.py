"""Synthetic CRDT workload generator — benchmark corpora.

Generates `examples/chat`-shaped documents (BASELINE.json configs 1/3/4:
text-heavy multi-actor edit histories with LWW map churn) two ways:

- `synth_columns`: straight into numpy columnar form (fast; used to build
  the 10k-doc bench batches without 10M Python op objects). The histories
  are structurally valid: lamport-monotone ctrs, per-actor seq chains,
  RGA refs into prior elements, LWW pred chains per map key.
- `synth_changes`: the same shape as Change objects (used for the host
  baseline and for equivalence spot-checks between the two generators).

Both use the same parameterization so device-vs-host throughput compares
the same logical workload.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from ..crdt.change import HEAD, ROOT, Action, Change, Op, OpId
from .columnar import COLUMNS, PAD


def synth_columns(
    n_ops: int,
    n_actors: int = 3,
    ops_per_change: int = 10,
    text_frac: float = 0.85,
    n_keys: int = 10,
    seed: int = 0,
) -> Tuple[Dict[str, np.ndarray], np.ndarray, np.ndarray]:
    """One doc's history as columnar arrays (length n_ops) + pred edges.

    Row 0 is the MAKE_TEXT; remaining rows are text inserts (ref = a
    prior element or HEAD) or root map SETs (pred-chained per key).
    """
    rng = np.random.default_rng(seed)
    n = n_ops
    action = np.full(n, int(Action.SET), np.int32)
    obj = np.zeros(n, np.int32)
    key = np.full(n, -1, np.int32)
    ref = np.full(n, -3, np.int32)
    insert = np.zeros(n, np.int32)
    vkind = np.zeros(n, np.int32)
    value = np.zeros(n, np.int32)
    dt = np.zeros(n, np.int32)

    action[0] = int(Action.MAKE_TEXT)
    obj[0] = -1
    key[0] = n_keys  # key table: 0..n_keys-1 are map keys, n_keys = "t"

    is_text = rng.random(n) < text_frac
    is_text[0] = False
    text_rows = np.nonzero(is_text)[0]
    # k-th text row references a uniformly random earlier text row (RGA
    # chain/tree mix) or HEAD for the first
    k = np.arange(len(text_rows))
    pick = np.floor(rng.random(len(text_rows)) * k).astype(np.int64)
    refs = np.where(k == 0, -2, text_rows[np.minimum(pick, np.maximum(k - 1, 0))])
    ref[text_rows] = refs.astype(np.int32)
    insert[text_rows] = 1
    vkind[text_rows] = 3  # VK_STR
    value[text_rows] = rng.integers(0, 26, len(text_rows))  # char table idx

    map_rows = np.nonzero(~is_text)[0][1:]  # skip row 0
    mkeys = rng.integers(0, n_keys, len(map_rows)).astype(np.int32)
    key[map_rows] = mkeys
    vkind[map_rows] = 1  # VK_INT
    value[map_rows] = rng.integers(0, 1000, len(map_rows))

    # pred chains: each map SET supersedes the previous SET of its key
    psrc_list: List[int] = []
    ptgt_list: List[int] = []
    last_for_key: Dict[int, int] = {}
    for r, mk in zip(map_rows.tolist(), mkeys.tolist()):
        prev = last_for_key.get(mk)
        if prev is not None:
            psrc_list.append(r)
            ptgt_list.append(prev)
        last_for_key[mk] = r

    actor = ((np.arange(n) // ops_per_change) % n_actors).astype(np.int32)
    ctr = np.arange(1, n + 1, dtype=np.int32)
    # per-actor change seq: change index c = row // ops_per_change is the
    # (c // n_actors + 1)-th change of its actor
    change_idx = np.arange(n) // ops_per_change
    seq = (change_idx // n_actors + 1).astype(np.int32)

    cols = {
        "action": action,
        "actor": actor,
        "ctr": ctr,
        "seq": seq,
        "obj": obj,
        "key": key,
        "ref": ref,
        "insert": insert,
        "vkind": vkind,
        "value": value,
        "dt": dt,
    }
    psrc = np.asarray(psrc_list, np.int32)
    ptgt = np.asarray(ptgt_list, np.int32)
    return cols, psrc, ptgt


def synth_batch(
    n_docs: int,
    n_ops: int,
    n_actors: int = 3,
    distinct: int = 8,
    seed: int = 0,
    **kw,
):
    """A ColumnarBatch of n_docs synthetic docs (cycling `distinct`
    generated histories — throughput benchmarking doesn't need 10k unique
    histories, and generation stays O(distinct * n_ops))."""
    from .columnar import ColumnarBatch, _round_up

    protos = [
        synth_columns(n_ops, n_actors=n_actors, seed=seed + i, **kw)
        for i in range(min(distinct, n_docs))
    ]
    N = _round_up(n_ops)
    P_len = _round_up(max(max(len(p[1]) for p in protos), 1))
    D = n_docs
    cols = {name: np.zeros((D, N), np.int32) for name in COLUMNS}
    cols["action"][:] = PAD
    cols["obj"][:] = -1
    cols["key"][:] = -1
    cols["ref"][:] = -3
    psrc = np.full((D, P_len), -1, np.int32)
    ptgt = np.full((D, P_len), -1, np.int32)
    for d in range(D):
        c, ps, pt = protos[d % len(protos)]
        for name in COLUMNS:
            cols[name][d, :n_ops] = c[name]
        psrc[d, : len(ps)] = ps
        ptgt[d, : len(pt)] = pt
    actors = [f"actor{i:02d}" for i in range(n_actors)]
    keys = [f"k{i}" for i in range(kw.get("n_keys", 10))] + ["t"]
    strings = [chr(97 + i) for i in range(26)]
    return ColumnarBatch(
        cols=cols,
        psrc=psrc,
        ptgt=ptgt,
        n_ops=np.full((D,), n_ops, np.int32),
        actors=actors,
        keys=keys,
        strings=strings,
        floats=[],
        bigints=[],
        doc_actors=np.tile(
            np.arange(n_actors, dtype=np.int32), (D, 1)
        ),
    )


def synth_changes(
    n_ops: int,
    n_actors: int = 3,
    ops_per_change: int = 10,
    text_frac: float = 0.85,
    n_keys: int = 10,
    seed: int = 0,
) -> List[Change]:
    """The same workload as Change objects (host-baseline replay)."""
    cols, psrc, ptgt = synth_columns(
        n_ops, n_actors, ops_per_change, text_frac, n_keys, seed
    )
    actors = [f"actor{i:02d}" for i in range(n_actors)]
    keys = [f"k{i}" for i in range(n_keys)] + ["t"]
    strings = [chr(97 + i) for i in range(26)]
    pred_of: Dict[int, List[int]] = {}
    for s, t in zip(psrc.tolist(), ptgt.tolist()):
        pred_of.setdefault(s, []).append(t)

    def opid(row: int) -> OpId:
        return OpId(int(cols["ctr"][row]), actors[int(cols["actor"][row])])

    changes: List[Change] = []
    clock: Dict[str, int] = {}
    row = 0
    n = n_ops
    while row < n:
        end = min(row + ops_per_change, n)
        a = actors[int(cols["actor"][row])]
        seq = int(cols["seq"][row])
        ops = []
        for r in range(row, end):
            act = Action(int(cols["action"][r]))
            o = ROOT if cols["obj"][r] == -1 else opid(int(cols["obj"][r]))
            kid = int(cols["key"][r])
            rf = int(cols["ref"][r])
            ops.append(
                Op(
                    action=act,
                    obj=o,
                    key=keys[kid] if kid >= 0 else None,
                    ref=HEAD if rf == -2 else (opid(rf) if rf >= 0 else None),
                    insert=bool(cols["insert"][r]),
                    value=(
                        strings[int(cols["value"][r])]
                        if cols["vkind"][r] == 3
                        else int(cols["value"][r])
                        if cols["vkind"][r] == 1
                        else None
                    ),
                    pred=tuple(opid(t) for t in pred_of.get(r, ())),
                )
            )
        deps = {k: v for k, v in clock.items() if k != a}
        changes.append(
            Change(
                actor=a,
                seq=seq,
                start_op=int(cols["ctr"][row]),
                deps=deps,
                ops=tuple(ops),
            )
        )
        clock[a] = seq
        row = end
    return changes
