"""Columnar op-log encoding — changes as padded int32 tensors.

The bulk half of the dual-path design (SURVEY.md §7.1, BASELINE.json):
a document's change history becomes fixed-shape int32 columns that the
device kernels (ops/crdt_kernels.py) consume; `vmap` batches documents on a
leading axis; `pjit` shards that axis over the mesh (parallel/).

Row = one op, in a causal linear order (sorted by (start_op ctr, actor) —
valid because a change depending on another always has a larger start_op).

Columns (all int32, shape [N] per doc, padded with PAD rows):
  action  Action code (change.Action; PAD=7)
  actor   index into the batch actor table
  ctr     lamport counter (op id = (ctr, actor))
  seq     change seq the op belongs to (for device clock derivation)
  obj     row index of the container's MAKE op; -1 = root map
  key     index into the batch key-string table; -1 = none (list ops)
  ref     row index: INS -> predecessor elem row (-2 = HEAD);
          SET/DEL on elem -> elem row; INC -> target value-op row; else -3
  insert  1 if the op creates a new list/text element
  vkind   value encoding kind (VK_*)
  value   inline small int / bool / index into a side table
  dt      datatype code: 0 none, 1 counter, 2 timestamp

Supersession (pred) edges are their own arrays [P]: psrc (superseding row),
ptgt (superseded row), padded with (-1, -1). INC ops contribute NO pred
edges — their target rides the ref column (an INC must not kill its
counter).

Side tables (batch-global, host-side): actors, key strings, value strings,
floats (float64 — no precision loss through the device path), bigints.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..crdt.change import HEAD, ROOT, Action, Change, OpId

PAD = int(Action.PAD)

# value kinds
VK_NONE = 0
VK_INT = 1  # inline int32
VK_FLOAT = 2  # index into floats table
VK_STR = 3  # index into strings table
VK_BOOL = 4  # inline 0/1
VK_BIGINT = 5  # index into bigints table
# MAKE_* rows carry no value (the op id is the object id)

_INT32_MIN, _INT32_MAX = -(2**31), 2**31 - 1

COLUMNS = (
    "action",
    "actor",
    "ctr",
    "seq",
    "obj",
    "key",
    "ref",
    "insert",
    "vkind",
    "value",
    "dt",
)


class _Interner:
    def __init__(self) -> None:
        self.items: List[Any] = []
        self._index: Dict[Any, int] = {}

    def __call__(self, item: Any) -> int:
        idx = self._index.get(item)
        if idx is None:
            idx = len(self.items)
            self.items.append(item)
            self._index[item] = idx
        return idx

    def __len__(self) -> int:
        return len(self.items)


@dataclass
class ColumnarBatch:
    """[D, N] padded op columns + [D, P] pred edges + side tables.

    `doc_actors` is the per-doc local actor map: [D, A_loc] int32
    indices into `actors`, ascending (== actor-string sort order, the
    device tie-break), padded with -1. The device kernels only ever see
    A_loc (max actors per doc, a small constant) — never the batch-wide
    actor count — so the jit bucket and the [D, A_loc] clock output stay
    independent of how many documents share a slab."""

    cols: Dict[str, np.ndarray]
    psrc: np.ndarray
    ptgt: np.ndarray
    n_ops: np.ndarray  # [D] real (unpadded) op counts
    actors: List[str]
    keys: List[str]
    strings: List[str]
    floats: List[float]
    bigints: List[int]
    op_actor_ids: List[List[str]] = field(default_factory=list)
    doc_actors: Optional[np.ndarray] = None  # [D, A_loc] int32, -1 pad
    slot: Optional[np.ndarray] = None  # [D, N] int16 local actor slots

    @property
    def shape(self) -> Tuple[int, int]:
        return self.cols["action"].shape  # (D, N)

    @property
    def n_docs(self) -> int:
        return self.shape[0]

    @property
    def n_rows(self) -> int:
        return self.shape[1]


def causal_sort(changes: Sequence[Change]) -> List[Change]:
    """Deduplicate by (actor, seq) and sort into a causal linear order.

    (start_op, actor) is a valid linear extension: if X depends on Y then
    X.start_op > Y.max_op >= Y.start_op (lamport assignment in
    OpSet.apply_local_request)."""
    seen = {}
    for c in changes:
        seen.setdefault((c.actor, c.seq), c)
    return sorted(seen.values(), key=lambda c: (c.start_op, c.actor))


def pack_docs(
    docs_changes: Sequence[Sequence[Change]],
    n_rows: Optional[int] = None,
    n_pred: Optional[int] = None,
) -> ColumnarBatch:
    """Pack many documents' histories into one padded batch."""
    actor_ids = _Interner()
    key_ids = _Interner()
    str_ids = _Interner()
    float_ids = _Interner()
    big_ids = _Interner()

    per_doc: List[Tuple[Dict[str, List[int]], List[Tuple[int, int]]]] = []
    for changes in docs_changes:
        per_doc.append(
            _pack_one(
                causal_sort(changes), actor_ids, key_ids, str_ids, float_ids,
                big_ids,
            )
        )

    # Device kernels tie-break concurrent ops by actor *index* (the
    # composite ctr*A + actor); the host OpSet tie-breaks by actor *string*
    # (OpId ordering). Remap indices so index order == string sort order.
    sorted_actors = sorted(actor_ids.items)
    lut = np.zeros(max(len(actor_ids.items), 1), dtype=np.int32)
    for old, name in enumerate(actor_ids.items):
        lut[old] = sorted_actors.index(name)
    for doc_cols, _ in per_doc:
        doc_cols["actor"] = [int(lut[a]) for a in doc_cols["actor"]]
    actor_ids.items = sorted_actors

    max_ops = max((len(d[0]["action"]) for d in per_doc), default=0)
    max_preds = max((len(d[1]) for d in per_doc), default=0)
    N = n_rows if n_rows is not None else _round_up(max(max_ops, 1))
    P = n_pred if n_pred is not None else _round_up(max(max_preds, 1))
    if max_ops > N or max_preds > P:
        raise ValueError(
            f"doc exceeds bucket: ops {max_ops}>{N} or preds {max_preds}>{P}"
        )

    D = len(per_doc)
    cols = {name: np.full((D, N), 0, dtype=np.int32) for name in COLUMNS}
    cols["action"][:] = PAD
    cols["obj"][:] = -1
    cols["key"][:] = -1
    cols["ref"][:] = -3
    psrc = np.full((D, P), -1, dtype=np.int32)
    ptgt = np.full((D, P), -1, dtype=np.int32)
    n_ops = np.zeros((D,), dtype=np.int32)

    doc_actor_sets: List[List[int]] = []
    for d, (doc_cols, preds) in enumerate(per_doc):
        n = len(doc_cols["action"])
        n_ops[d] = n
        for name in COLUMNS:
            cols[name][d, :n] = doc_cols[name]
        for k, (s, t) in enumerate(preds):
            psrc[d, k] = s
            ptgt[d, k] = t
        doc_actor_sets.append(sorted(set(doc_cols["actor"])))

    return ColumnarBatch(
        cols=cols,
        psrc=psrc,
        ptgt=ptgt,
        n_ops=n_ops,
        actors=list(actor_ids.items),
        keys=list(key_ids.items),
        strings=list(str_ids.items),
        floats=list(float_ids.items),
        bigints=list(big_ids.items),
        doc_actors=pack_doc_actor_map(doc_actor_sets),
    )


def pack_doc_actor_map(doc_actor_sets: Sequence[Sequence[int]]) -> np.ndarray:
    """[D, A_loc] int32 local actor map from per-doc ascending actor-index
    lists; -1 pads. A_loc = max actors in any one doc (min 1)."""
    D = len(doc_actor_sets)
    a_loc = max((len(s) for s in doc_actor_sets), default=1)
    out = np.full((D, max(a_loc, 1)), -1, np.int32)
    for d, s in enumerate(doc_actor_sets):
        out[d, : len(s)] = s
    return out


def round_up_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


_round_up = round_up_pow2


def doc_actor_map_from_pairs(
    pairs: np.ndarray, A: int, Dp: int
) -> np.ndarray:
    """[Dp, A_loc] local actor map from sorted unique (doc*A + actor)
    composites; ascending within a doc (== actor-string sort order when
    actor indices index a sorted actor table), -1 pads."""
    pair_doc = pairs // A
    pair_counts = np.bincount(pair_doc, minlength=Dp).astype(np.int64)
    A_loc = int(pair_counts.max(initial=1))
    pair_starts = np.zeros(Dp + 1, np.int64)
    np.cumsum(pair_counts, out=pair_starts[1:])
    out = np.full(Dp * max(A_loc, 1), -1, np.int32)
    slot = np.arange(len(pairs), dtype=np.int64) - pair_starts[pair_doc]
    out[pair_doc * A_loc + slot] = (pairs % A).astype(np.int32)
    return out.reshape(Dp, max(A_loc, 1))


def _encode_op_row(
    op,
    opid: OpId,
    change: Change,
    row_of: Dict[OpId, int],
    actor_ids: _Interner,
    key_ids: _Interner,
    str_ids: _Interner,
    float_ids: _Interner,
    big_ids: _Interner,
) -> Optional[Tuple[Dict[str, int], List[int]]]:
    """Resolve + encode ONE op as ({column: value}, pred target rows).
    None when the op drops (unknown container/element/INC target — the
    OpSet tolerance). THE row encoding: `_pack_one` (bulk reference
    pack) and `LiveColumns._append_one` (live engine appends) both
    write exactly these values, so the two paths cannot drift."""
    if op.obj == ROOT:
        obj_row = -1
    else:
        obj_row = row_of.get(op.obj, -4)
        if obj_row == -4:
            return None  # container unknown (tolerate, like OpSet)
    if op.action == Action.INC:
        target = op.pred[0] if op.pred else None
        ref_row = row_of.get(target, -3) if target else -3
        if ref_row == -3:
            return None
    elif op.ref is None:
        ref_row = -3
    elif op.ref == HEAD:
        ref_row = -2
    else:
        ref_row = row_of.get(op.ref, -4)
        if ref_row == -4:
            return None  # unknown element
    vkind, value = _encode_value(op, str_ids, float_ids, big_ids)
    vals = {
        "action": int(op.action),
        "actor": actor_ids(change.actor),
        "ctr": opid.ctr,
        "seq": change.seq,
        "obj": obj_row,
        "key": key_ids(op.key) if op.key is not None else -1,
        "ref": ref_row,
        "insert": 1 if op.insert else 0,
        "vkind": vkind,
        "value": value,
        "dt": (
            1 if op.datatype == "counter"
            else 2 if op.datatype == "timestamp" else 0
        ),
    }
    pred_tgts: List[int] = []
    if op.action != Action.INC:
        for p in op.pred:
            tgt = row_of.get(p)
            if tgt is not None:
                pred_tgts.append(tgt)
    return vals, pred_tgts


def _pack_one(
    changes: List[Change],
    actor_ids: _Interner,
    key_ids: _Interner,
    str_ids: _Interner,
    float_ids: _Interner,
    big_ids: _Interner,
) -> Tuple[Dict[str, List[int]], List[Tuple[int, int]]]:
    cols: Dict[str, List[int]] = {name: [] for name in COLUMNS}
    preds: List[Tuple[int, int]] = []
    row_of: Dict[OpId, int] = {}
    row = 0
    for change in changes:
        for i, op in enumerate(change.ops):
            opid = change.op_id(i)
            enc = _encode_op_row(
                op, opid, change, row_of,
                actor_ids, key_ids, str_ids, float_ids, big_ids,
            )
            if enc is None:
                continue
            vals, pred_tgts = enc
            for name in COLUMNS:
                cols[name].append(vals[name])
            for tgt in pred_tgts:
                preds.append((row, tgt))
            row_of[opid] = row
            row += 1
    return cols, preds


def _encode_value(op, str_ids, float_ids, big_ids) -> Tuple[int, int]:
    v = op.value
    if op.action.makes_object or v is None:
        return VK_NONE, 0
    if isinstance(v, bool):
        return VK_BOOL, 1 if v else 0
    if isinstance(v, int):
        if _INT32_MIN <= v <= _INT32_MAX:
            return VK_INT, v
        return VK_BIGINT, big_ids(v)
    if isinstance(v, float):
        return VK_FLOAT, float_ids(v)
    if isinstance(v, str):
        return VK_STR, str_ids(v)
    # fallthrough: non-scalar payloads shouldn't occur (containers are MAKE
    # ops); encode their repr so nothing crashes
    return VK_STR, str_ids(repr(v))


# ---------------------------------------------------------------------------
# vectorized bulk packing from columnar feed caches (storage/colcache.py)
#
# The per-op Python loop above (`pack_docs`) is the correctness reference;
# this path packs the same batch from FeedColumns sidecars with numpy only:
# window slicing by searchsorted, one flat causal argsort across all docs,
# and OpId -> row resolution via a sorted composite-key lookup. This is
# what makes the 10k-doc cold start feed->device path real (BASELINE
# config 4): zero per-op host work.


def _prefix_single_ok(fc) -> bool:
    """True if a feed qualifies for the no-sort prefix pack: every op's
    container/element/pred references stay inside the feed (single-writer
    history), and ctr is strictly increasing (commit order == causal
    order). Cached on the FeedColumns object.

    The cache is an idempotent latch, safe under concurrent pack
    workers (HM_PACK_WORKERS>1, guard manifest entry for FeedColumns):
    racing callers compute the same bool from immutable planes and the
    attribute rebind is GIL-atomic, so the worst case is duplicate
    compute, never a torn or wrong value."""
    ok = getattr(fc, "_prefix_single_ok", None)
    if ok is None:
        n = fc.n_rows
        ctr = fc.plane("ctr")
        ok = bool(
            np.all(fc.plane("obj_a") <= 0)  # obj actor: ROOT or writer
            and np.all(fc.plane("ref_a") <= 0)  # writer or sentinel
            # dense lamport counters: row i is op ctr i+1, so references
            # resolve as ctr-1 with no search
            and np.array_equal(
                ctr, np.arange(1, n + 1, dtype=ctr.dtype)
            )
            and (len(fc.preds) == 0 or np.all(fc.preds[:, 2] == 0))
        )
        fc._prefix_single_ok = ok
    return ok


_DT_CODE = {
    np.dtype(np.int8): 0,
    np.dtype(np.int16): 1,
    np.dtype(np.int32): 2,
    np.dtype(np.uint8): 3,
}

# source plane order of the native pack entry (hm_native.cpp hm_pack_prefix)
_PACK_SRC_PLANES = (
    "action", "ctr", "seq", "obj_ctr", "obj_a", "key",
    "ref_ctr", "ref_a", "insert", "vkind", "value", "dt",
)

_pack_src_idx_cache: Optional[np.ndarray] = None


def _pack_src_idx() -> np.ndarray:
    """Indices of the native pack's source planes within the sidecar's
    PLANE_NAMES order (what FeedColumns.plane_meta offsets follow).

    Thread-safety (pack pool, HM_PACK_WORKERS>1): compute-local, then
    ONE assignment publishes — concurrent first callers may each build
    the (identical, immutable) array, but no caller can ever observe a
    half-built cache; the module-global rebind is GIL-atomic."""
    global _pack_src_idx_cache
    got = _pack_src_idx_cache
    if got is None:
        from ..storage.colcache import PLANE_NAMES

        got = np.asarray(
            [PLANE_NAMES.index(n) for n in _PACK_SRC_PLANES], np.int64
        )
        _pack_src_idx_cache = got
    return got


def _native_pack_lib():
    if os.environ.get("HM_NATIVE_PACK", "1") == "0":
        return None
    from .. import native

    return native.pack_lib()


def _pack_wire_dtypes(i16ok, row_dt, kdt, vmin, vmax):
    return {
        "action": np.uint8,
        "insert": np.uint8,
        "vkind": np.uint8,
        "dt": np.uint8,
        "actor": np.int32,  # batch-global ids (host/decode only)
        "ctr": row_dt,
        "seq": row_dt,
        "obj": row_dt,
        "key": kdt,
        "ref": row_dt,
        "value": (
            np.int16
            if i16ok and -(2**15) <= vmin and vmax < 2**15
            else np.int32
        ),
    }


def _native_pack_prefix(
    lib, fcs, fc_idx_a, ends, writer_g, flat_lut,
    D, Dp, N, i16ok, row_dt, kdt,
) -> Dict[str, np.ndarray]:
    """Emit the padded [Dp, N] column planes through the C++ batch entry
    point: per-feed narrow plane pointers in, preallocated output buffers
    filled in place (real rows AND pad cells — no np.full prepass, no [M]
    intermediates). Returns {} when a plane can't be described to the
    native ABI (caller falls back to the numpy twin)."""
    F = len(fcs)
    srcs = np.empty((F, len(_PACK_SRC_PLANES)), np.int64)
    sdts = np.empty((F, len(_PACK_SRC_PLANES)), np.uint8)
    keep_alive = []  # converted planes must outlive the call
    src_idx = _pack_src_idx()
    for i, fc in enumerate(fcs):
        meta = fc.plane_meta
        if meta is not None:
            # every plane is a slice of one checkpoint buffer: all 12
            # pointers derive from the base address in two gathers
            base_addr, offs, dts = meta[0], meta[1], meta[2]
            srcs[i] = base_addr + offs[src_idx]
            sdts[i] = dts[src_idx]
            keep_alive.append(meta)
            continue
        planes = fc.planes
        for j, name in enumerate(_PACK_SRC_PLANES):
            p = planes[name]
            code = _DT_CODE.get(p.dtype)
            if code is None or not p.flags["C_CONTIGUOUS"]:
                p = np.ascontiguousarray(p, np.int32)
                keep_alive.append(p)
                code = 2
            srcs[i, j] = p.__array_interface__["data"][0]
            sdts[i, j] = code

    # a corrupt sidecar whose row_ends overrun its planes must not reach
    # the C loops (the numpy twin fails loudly on the length mismatch)
    feed_rows = np.asarray([fc.n_rows for fc in fcs], np.int64)
    if np.any(ends > feed_rows[fc_idx_a]):
        return {}

    klut, koffs = flat_lut("k")
    slut, soffs = flat_lut("s")
    flut, foffs = flat_lut("f")
    blut, boffs = flat_lut("b")
    lut_lens = np.asarray(
        [len(klut), len(slut), len(flut), len(blut)], np.int64
    )
    writer_g = np.ascontiguousarray(writer_g, np.int64)
    ends = np.ascontiguousarray(ends, np.int64)
    fc_idx_a = np.ascontiguousarray(fc_idx_a, np.int64)

    def ptr(a):
        return a.__array_interface__["data"][0]

    mm = np.zeros(2, np.int64)
    rc = lib.hm_pack_value_minmax(
        D, ptr(fc_idx_a), ptr(ends), ptr(srcs), ptr(sdts),
        ptr(slut), ptr(soffs), ptr(flut), ptr(foffs), ptr(blut),
        ptr(boffs), ptr(lut_lens), ptr(mm),
    )
    if rc != 0:
        return {}
    dtypes = _pack_wire_dtypes(i16ok, row_dt, kdt, int(mm[0]), int(mm[1]))

    cols: Dict[str, np.ndarray] = {}
    out_ptrs = np.empty(len(COLUMNS), np.int64)
    out_dts = np.empty(len(COLUMNS), np.uint8)
    for ci, name in enumerate(COLUMNS):
        arr = np.empty(Dp * N, dtypes[name])
        cols[name] = arr
        out_ptrs[ci] = arr.__array_interface__["data"][0]
        out_dts[ci] = _DT_CODE[arr.dtype]
    rc = lib.hm_pack_prefix(
        D, Dp, N, ptr(fc_idx_a), ptr(ends), ptr(srcs), ptr(sdts),
        ptr(klut), ptr(koffs), ptr(slut), ptr(soffs), ptr(flut),
        ptr(foffs), ptr(blut), ptr(boffs), ptr(lut_lens),
        ptr(writer_g), ptr(out_ptrs), ptr(out_dts),
    )
    del keep_alive
    if rc != 0:
        return {}
    return {
        name: cols[name].reshape(Dp, N) for name in COLUMNS
    }


def _try_pack_prefix_single(
    doc_specs, n_rows, n_pred, n_docs, device=None
) -> Optional[ColumnarBatch]:
    """Fast pack for the dominant cold-open shape: one single-writer feed
    per doc, whole-prefix windows. Rows are already in causal order (ctr
    ascending) and every reference resolves within the prefix (causal
    lamport property: a referenced op always has a smaller ctr), so this
    path needs ZERO sorts and no drop fixpoint — the general path's two
    M-sized argsorts and composite-key resolution collapse into one
    searchsorted over an already-sorted key.

    The padded-plane emit itself has three bit-identical twins, tried
    in order: the jitted device kernel (ops/pack_kernels.py, only when
    HM_DEVICE_PACK=1 — host work collapses to narrow-plane concats),
    the C++ batch entry point (native/src/hm_native.cpp hm_pack_prefix
    — one fused pass per column straight from the feeds' narrow planes
    into preallocated output buffers), and the numpy scatter below (the
    fallback when the native layer is absent, HM_NATIVE_PACK=0, or a
    feed is not plane-backed). `device` is the mesh scheduler's
    placement hint for the device twin; host twins ignore it."""
    for spec in doc_specs:
        if len(spec) != 1:
            return None
        fc, s, _e = spec[0]
        if s != 0 or not _prefix_single_ok(fc):
            return None

    D = len(doc_specs)
    Dp = max(n_docs, D) if n_docs is not None else D

    fcs: List[Any] = []
    fc_idx: List[int] = []
    fc_of: Dict[int, int] = {}
    ends = np.zeros(D, np.int64)  # prefix row counts
    for d, spec in enumerate(doc_specs):
        fc, _s, e = spec[0]
        i = fc_of.get(id(fc))
        if i is None:
            i = fc_of[id(fc)] = len(fcs)
            fcs.append(fc)
        fc_idx.append(i)
        ends[d] = fc.window(0, e)[1]

    # -- global tables (same interning as the general path). Feeds
    # instantiated from shared templates carry IDENTICAL local tables,
    # so the per-item interning loop memoizes on the table tuple — the
    # global id sequence is unchanged (a memo hit means every item was
    # already interned, in the same order).
    actor_int = _Interner()
    key_int = _Interner()
    str_int = _Interner()
    float_int = _Interner()
    big_int = _Interner()
    luts = {"k": [], "s": [], "f": [], "b": []}
    writers: List[int] = []
    lut_memo: Dict[Any, np.ndarray] = {}

    def lut_of(kind, interner, items):
        key = (kind, tuple(items))
        got = lut_memo.get(key)
        if got is None:
            got = np.asarray([interner(x) for x in items], np.int64)
            lut_memo[key] = got
        return got

    writer_memo: Dict[Any, int] = {}
    for fc in fcs:
        akey = tuple(fc.actors)
        w = writer_memo.get(akey)
        if w is None:
            for x in fc.actors:
                actor_int(x)
            w = actor_int(fc.actors[0]) if fc.actors else 0
            writer_memo[akey] = w
        writers.append(w)
        luts["k"].append(lut_of("k", key_int, fc.keys))
        luts["s"].append(lut_of("s", str_int, fc.strings))
        luts["f"].append(lut_of("f", float_int, fc.floats))
        luts["b"].append(lut_of("b", big_int, fc.bigints))
    sorted_actors = sorted(actor_int.items)
    rank_of = {name: i for i, name in enumerate(sorted_actors)}
    arank = np.asarray(
        [rank_of[a] for a in actor_int.items], np.int64
    )
    writer_g = (
        arank[np.asarray(writers, np.int64)]
        if writers
        else np.zeros(0, np.int64)
    )

    M = int(ends.sum())
    if M == 0:
        N = n_rows if n_rows is not None else 1
        P = n_pred if n_pred is not None else 1
        return _empty_batch(
            Dp, N, P, sorted_actors, key_int, str_int, float_int, big_int
        )

    fc_idx_a = np.asarray(fc_idx, np.int64)

    from ..storage.colcache import OBJ_ROOT, REF_HEAD, REF_NONE

    # -- preds ----------------------------------------------------------
    pr_docs_l: List[int] = []
    pr_cnt_l: List[int] = []
    pr_rows: List[np.ndarray] = []
    for d in range(D):
        fc = fcs[fc_idx[d]]
        n_pr = len(fc.preds)
        if not n_pr:
            continue
        e = int(ends[d])
        phi = (
            n_pr  # whole-prefix window: every pred src is inside it
            if e >= fc.n_rows
            else int(np.searchsorted(fc.preds[:, 0], e, side="left"))
        )
        if phi:
            pr_rows.append(fc.preds[:phi])
            pr_docs_l.append(d)
            pr_cnt_l.append(phi)
    if pr_rows:
        PR = np.concatenate(pr_rows, axis=0)
        pr_doc = np.repeat(
            np.asarray(pr_docs_l, np.int64), np.asarray(pr_cnt_l, np.int64)
        )
        p_src_row = PR[:, 0].astype(np.int64)  # feed row == doc row
        p_tgt_row = PR[:, 1].astype(np.int64) - 1  # dense ctr -> row
        pred_counts = np.bincount(pr_doc, minlength=Dp).astype(np.int64)
        pred_starts = np.zeros(Dp + 1, np.int64)
        np.cumsum(pred_counts, out=pred_starts[1:])
        p_pos = np.arange(len(pr_doc), dtype=np.int64) - pred_starts[pr_doc]
    else:
        pred_counts = np.zeros(Dp, np.int64)
        p_src_row = p_tgt_row = p_pos = pr_doc = np.zeros(0, np.int64)

    # -- bucket shapes ---------------------------------------------------
    max_ops = int(ends.max(initial=0))
    max_preds = int(pred_counts.max(initial=0))
    N = n_rows if n_rows is not None else _round_up(max(max_ops, 1))
    P = n_pred if n_pred is not None else _round_up(max(max_preds, 1))
    if max_ops > N or max_preds > P:
        raise ValueError(
            f"doc exceeds bucket: ops {max_ops}>{N} or preds {max_preds}>{P}"
        )

    # wire dtypes are a function of the bucket + value ranges so native
    # and numpy twins allocate identically (host_args passes the planes
    # through copy-free): everything row-indexed fits int16 when N < 32k
    # — the common case — and flags planes fit uint8
    i16ok = N < 2**15
    row_dt = np.int16 if i16ok else np.int32
    kdt = np.int16 if len(key_int.items) < 2**15 else np.int32

    def flat_lut(kind):
        offs = np.zeros(len(fcs) + 1, np.int64)
        for i, l in enumerate(luts[kind]):
            offs[i + 1] = offs[i] + len(l)
        flat = (
            np.concatenate(luts[kind])
            if any(len(l) for l in luts[kind])
            else np.zeros(1, np.int64)
        )
        return flat, offs

    use_planes = all(fc.planes is not None for fc in fcs)
    native_lib = _native_pack_lib() if use_planes else None
    cols: Dict[str, np.ndarray] = {}

    from .pack_kernels import device_pack_enabled

    if device_pack_enabled():
        from .pack_kernels import device_pack_prefix

        cols = device_pack_prefix(
            fcs, fc_idx, fc_idx_a, ends, writer_g, flat_lut,
            D, Dp, N, i16ok, row_dt, kdt, device,
        )

    if not cols and native_lib is not None:
        cols = _native_pack_prefix(
            native_lib, fcs, fc_idx_a, ends, writer_g, flat_lut,
            D, Dp, N, i16ok, row_dt, kdt,
        )

    if not cols:  # numpy twin (fallback, and the fuzz reference)
        doc_col = np.repeat(np.arange(D, dtype=np.int64), ends)
        doc_starts = np.zeros(D + 1, np.int64)
        np.cumsum(ends, out=doc_starts[1:])
        pos = (
            np.arange(M, dtype=np.int64) - doc_starts[doc_col]
        ).astype(np.int32)
        flat_idx = doc_col * N + pos

        # column sources: v3 plane-backed feeds serve each column as a
        # contiguous narrow array (concat promotes mixed widths); v2
        # feeds fall back to strided slices of the dense row matrix.
        if use_planes:
            def col(name):
                return np.concatenate(
                    [
                        fcs[fc_idx[d]].plane(name)[: ends[d]]
                        for d in range(D)
                    ]
                )
        else:
            R = np.concatenate(
                [
                    fcs[fc_idx[d]].ensure_rows()[: ends[d]]
                    for d in range(D)
                ],
                axis=0,
            )
            from ..storage.colcache import PLANE_NAMES

            def col(name):
                return R[:, PLANE_NAMES.index(name)]

        # -- derived columns, computed in (near-)wire dtypes ------------
        obj_a = col("obj_a")
        obj_row = np.where(
            obj_a == 0, col("obj_ctr").astype(row_dt) - 1, row_dt(OBJ_ROOT)
        )
        del obj_a
        ref_a = col("ref_a")
        ref_row = np.where(
            ref_a == 0,
            col("ref_ctr").astype(row_dt) - 1,
            np.where(
                ref_a == -2, row_dt(REF_HEAD), row_dt(REF_NONE)
            ).astype(row_dt),
        )
        del ref_a

        # -- key/value global remap -------------------------------------
        klut, koffs = flat_lut("k")
        key_l = col("key").astype(np.int64)
        off_doc = np.repeat(koffs[fc_idx_a], ends)
        safe = np.minimum(np.maximum(off_doc + key_l, 0), len(klut) - 1)
        key_g = np.where(key_l >= 0, klut[safe].astype(kdt), kdt(-1))
        del safe, off_doc, key_l
        vkind = col("vkind")
        value_g = col("value").astype(np.int64)
        from ..storage.colcache import VK_BIGINT, VK_FLOAT, VK_STR

        for code, kind in ((VK_STR, "s"), (VK_FLOAT, "f"), (VK_BIGINT, "b")):
            m = vkind == code
            if m.any():
                lut, offs = flat_lut(kind)
                oc = np.repeat(offs[fc_idx_a], ends)
                value_g[m] = lut[oc[m] + value_g[m]]

        # -- scatter into padded [Dp, N] --------------------------------
        defaults = {"action": PAD, "obj": -1, "key": -1, "ref": -3}
        sources = {
            "action": col("action"),
            "actor": np.repeat(writer_g[fc_idx_a], ends),
            "ctr": col("ctr"), "seq": col("seq"), "obj": obj_row,
            "key": key_g, "ref": ref_row, "insert": col("insert"),
            "vkind": vkind, "value": value_g, "dt": col("dt"),
        }
        vmin = int(value_g.min(initial=0))
        vmax = int(value_g.max(initial=0))
        dtypes = _pack_wire_dtypes(i16ok, row_dt, kdt, vmin, vmax)
        for name in COLUMNS:
            flat = np.full(Dp * N, defaults.get(name, 0), dtypes[name])
            flat[flat_idx] = sources[name]
            cols[name] = flat.reshape(Dp, N)
    pdt = np.int16 if i16ok else np.int32
    psrc = np.full(Dp * P, -1, pdt)
    ptgt = np.full(Dp * P, -1, pdt)
    if len(p_src_row):
        pidx = pr_doc * P + p_pos
        psrc[pidx] = p_src_row
        ptgt[pidx] = p_tgt_row

    doc_actors = np.full((Dp, 1), -1, np.int32)
    doc_actors[:D, 0] = writer_g.astype(np.int32)[fc_idx_a]
    n_ops = np.zeros(Dp, np.int32)
    n_ops[:D] = ends
    batch = ColumnarBatch(
        cols=cols,
        psrc=psrc.reshape(Dp, P),
        ptgt=ptgt.reshape(Dp, P),
        n_ops=n_ops,
        actors=list(sorted_actors),
        keys=list(key_int.items),
        strings=list(str_int.items),
        floats=list(float_int.items),
        bigints=list(big_int.items),
        doc_actors=doc_actors,
    )
    batch.slot = np.zeros((Dp, N), np.int8)  # single writer: slot 0
    return batch


def pack_docs_columns(
    doc_specs: Sequence[Sequence[Tuple[Any, int, float]]],
    n_rows: Optional[int] = None,
    n_pred: Optional[int] = None,
    n_docs: Optional[int] = None,
    device: Optional[Any] = None,
) -> ColumnarBatch:
    """Pack documents from columnar feed windows.

    doc_specs[d] = [(FeedColumns, start_seq, end_seq), ...] — one entry
    per actor feed in the doc's cursor; the window is (start_seq,
    end_seq] like Actor.changes_in_window. Produces a ColumnarBatch
    equivalent (same device-kernel results and decoded patches) to
    `pack_docs` over the same histories.

    `n_docs` pads the doc axis with empty (all-PAD) documents — slab
    loaders bucket the batch shape so every slab reuses one compiled
    kernel executable.

    Single-writer whole-prefix loads (the dominant cold-open shape)
    dispatch to a no-sort fast path; anything else takes the general
    sorted-composite path below. `device` is a placement hint for the
    fast path's device pack kernel (HM_DEVICE_PACK=1): the chip the
    mesh scheduler will dispatch this slab to. Host packs — and the
    general path, which never runs on device — ignore it.
    """
    fast = _try_pack_prefix_single(
        doc_specs, n_rows, n_pred, n_docs, device
    )
    if fast is not None:
        return fast
    from ..storage.colcache import (
        OBJ_ROOT,
        REF_HEAD,
        REF_NONE,
        VK_BIGINT,
        VK_FLOAT,
        VK_STR,
    )

    D = len(doc_specs)
    Dp = max(n_docs, D) if n_docs is not None else D

    # -- global tables + per-feed LUTs ---------------------------------
    fcs: List[Any] = []
    fc_of: Dict[int, int] = {}
    for spec in doc_specs:
        for fc, _s, _e in spec:
            if id(fc) not in fc_of:
                fc_of[id(fc)] = len(fcs)
                fcs.append(fc)

    actor_int = _Interner()
    key_int = _Interner()
    str_int = _Interner()
    float_int = _Interner()
    big_int = _Interner()
    luts = {"a": [], "k": [], "s": [], "f": [], "b": []}
    for fc in fcs:
        luts["a"].append(
            np.asarray([actor_int(x) for x in fc.actors], np.int64)
        )
        luts["k"].append(
            np.asarray([key_int(x) for x in fc.keys], np.int64)
        )
        luts["s"].append(
            np.asarray([str_int(x) for x in fc.strings], np.int64)
        )
        luts["f"].append(
            np.asarray([float_int(x) for x in fc.floats], np.int64)
        )
        luts["b"].append(
            np.asarray([big_int(x) for x in fc.bigints], np.int64)
        )

    # actor index order must equal actor string sort order (device
    # tie-break parity — same remap as pack_docs)
    sorted_actors = sorted(actor_int.items)
    rank_of = {name: i for i, name in enumerate(sorted_actors)}
    arank = np.asarray(
        [rank_of[a] for a in actor_int.items], np.int64
    )
    luts["a"] = [
        arank[l] if len(l) else l for l in luts["a"]
    ]

    def _flat_lut(kind: str) -> Tuple[np.ndarray, np.ndarray]:
        offs = np.zeros(len(fcs) + 1, np.int64)
        for i, l in enumerate(luts[kind]):
            offs[i + 1] = offs[i] + len(l)
        flat = (
            np.concatenate(luts[kind])
            if any(len(l) for l in luts[kind])
            else np.zeros(1, np.int64)
        )
        return flat, offs

    alut, aoffs = _flat_lut("a")
    klut, koffs = _flat_lut("k")
    slut, soffs = _flat_lut("s")
    flut, foffs = _flat_lut("f")
    blut, boffs = _flat_lut("b")

    # -- gather window slices ------------------------------------------
    row_slices: List[np.ndarray] = []
    w_doc: List[int] = []
    w_fc: List[int] = []
    w_cnt: List[int] = []
    pred_slices: List[np.ndarray] = []
    p_doc: List[int] = []
    p_fc: List[int] = []
    p_cnt: List[int] = []
    p_base: List[int] = []
    flat_base = 0
    for d, spec in enumerate(doc_specs):
        seen = set()
        for fc, s, e in spec:
            fci = fc_of[id(fc)]
            if fci in seen:
                continue  # same feed listed twice: one window only
            seen.add(fci)
            lo, hi = fc.window(int(s), e)
            if hi <= lo:
                continue
            row_slices.append(fc.ensure_rows()[lo:hi])
            w_doc.append(d)
            w_fc.append(fci)
            w_cnt.append(hi - lo)
            psrc_col = fc.preds[:, 0]
            plo = int(np.searchsorted(psrc_col, lo, side="left"))
            phi = int(np.searchsorted(psrc_col, hi, side="left"))
            if phi > plo:
                pred_slices.append(fc.preds[plo:phi])
                p_doc.append(d)
                p_fc.append(fci)
                p_cnt.append(phi - plo)
                p_base.append(flat_base - lo)
            flat_base += hi - lo

    M = flat_base
    A = max(1, len(sorted_actors))
    if M == 0:
        N = n_rows if n_rows is not None else 1
        P = n_pred if n_pred is not None else 1
        return _empty_batch(
            Dp, N, P, sorted_actors, key_int, str_int, float_int, big_int
        )

    w_cnt_a = np.asarray(w_cnt, np.int64)
    w_doc_a = np.asarray(w_doc, np.int64)
    w_fc_a = np.asarray(w_fc, np.int64)
    R = np.concatenate(row_slices, axis=0)
    doc_col = np.repeat(w_doc_a, w_cnt_a)
    aoff_col = np.repeat(aoffs[w_fc_a], w_cnt_a)

    action = R[:, 0].astype(np.int64)
    ctr = R[:, 1].astype(np.int64)
    seqc = R[:, 2].astype(np.int64)
    start_op = R[:, 3].astype(np.int64)
    obj_ctr = R[:, 4].astype(np.int64)
    obj_a_l = R[:, 5].astype(np.int64)
    key_l = R[:, 6].astype(np.int64)
    ref_ctr = R[:, 7].astype(np.int64)
    ref_a_l = R[:, 8].astype(np.int64)
    insert = R[:, 9].astype(np.int64)
    vkind = R[:, 10].astype(np.int64)
    value_l = R[:, 11].astype(np.int64)
    dt = R[:, 12].astype(np.int64)

    # writer (op actor) = feed-local actor 0
    writer_g = np.asarray(
        [int(luts["a"][fci][0]) for fci in range(len(fcs))], np.int64
    )
    actor_g = np.repeat(writer_g[w_fc_a], w_cnt_a)

    def _lut_where(cond, lut, idx, alt):
        # np.where evaluates both branches: rows where cond is False
        # carry a sentinel local index (e.g. -1), and a feed whose table
        # is empty but sits at the end of the flat LUT would index one
        # past the end — clamp before gathering, select after.
        safe = np.minimum(np.maximum(idx, 0), len(lut) - 1)
        return np.where(cond, lut[safe], alt)

    obj_a_g = _lut_where(obj_a_l >= 0, alut, aoff_col + obj_a_l, obj_a_l)
    ref_a_g = _lut_where(ref_a_l >= 0, alut, aoff_col + ref_a_l, ref_a_l)
    key_g = _lut_where(
        key_l >= 0, klut, np.repeat(koffs[w_fc_a], w_cnt_a) + key_l, -1
    )
    value_g = value_l.copy()
    for code, lut, offs in (
        (VK_STR, slut, soffs),
        (VK_FLOAT, flut, foffs),
        (VK_BIGINT, blut, boffs),
    ):
        m = vkind == code
        if m.any():
            off_col = np.repeat(offs[w_fc_a], w_cnt_a)
            value_g[m] = lut[off_col[m] + value_l[m]]

    # preds (flat, pre-sort indices for src)
    if pred_slices:
        p_cnt_a = np.asarray(p_cnt, np.int64)
        p_fc_a = np.asarray(p_fc, np.int64)
        PR = np.concatenate(pred_slices, axis=0)
        pr_src = PR[:, 0].astype(np.int64) + np.repeat(
            np.asarray(p_base, np.int64), p_cnt_a
        )
        pr_tgt_ctr = PR[:, 1].astype(np.int64)
        pr_aoff = np.repeat(aoffs[p_fc_a], p_cnt_a)
        pr_tgt_a = alut[pr_aoff + PR[:, 2].astype(np.int64)]
        pr_doc = np.repeat(np.asarray(p_doc, np.int64), p_cnt_a)
    else:
        pr_src = pr_tgt_ctr = pr_tgt_a = pr_doc = np.zeros(0, np.int64)

    # -- composite key bit budget --------------------------------------
    ab = max(1, int(A - 1).bit_length())
    max_ctr = int(
        max(ctr.max(initial=0), obj_ctr.max(initial=0),
            ref_ctr.max(initial=0),
            int(pr_tgt_ctr.max(initial=0)))
    )
    cb = max(1, max_ctr.bit_length())
    db = max(1, int(Dp - 1).bit_length())
    if db + cb + ab > 62:
        raise ValueError(
            f"composite key overflow: docs={Dp} ctr={max_ctr} actors={A}"
        )

    def _rowkey(doc, c, a):
        return (doc << (cb + ab)) | (c << ab) | a

    need_obj = obj_a_l >= 0
    need_ref = ref_a_l >= 0

    def _resolve(rk_sorted, order_rk, q_doc, q_ctr, q_a):
        q = _rowkey(q_doc, q_ctr, np.maximum(q_a, 0))
        pos = np.searchsorted(rk_sorted, q)
        pos_c = np.minimum(pos, len(rk_sorted) - 1)
        hit = rk_sorted[pos_c] == q
        return order_rk[pos_c], hit

    # validity fixpoint: an op drops if its container or referenced
    # element is absent from the packed window (matches _pack_one's
    # incremental row_of misses, including the cascade)
    rk = _rowkey(doc_col, ctr, actor_g)
    order_rk = np.argsort(rk)
    rk_sorted = rk[order_rk]
    obj_tgt, obj_hit = _resolve(rk_sorted, order_rk, doc_col, obj_ctr, obj_a_g)
    ref_tgt, ref_hit = _resolve(rk_sorted, order_rk, doc_col, ref_ctr, ref_a_g)
    valid = np.ones(M, bool)
    while True:
        bad = (
            (need_obj & (~obj_hit | ~valid[obj_tgt]))
            | (need_ref & (~ref_hit | ~valid[ref_tgt]))
        ) & valid
        if not bad.any():
            break
        valid[bad] = False

    if not valid.all():
        keep = valid
        (
            action, ctr, seqc, start_op, obj_ctr, obj_a_g, key_g,
            ref_ctr, ref_a_g, insert, vkind, value_g, dt, actor_g,
            doc_col, need_obj, need_ref,
        ) = (
            x[keep]
            for x in (
                action, ctr, seqc, start_op, obj_ctr, obj_a_g, key_g,
                ref_ctr, ref_a_g, insert, vkind, value_g, dt, actor_g,
                doc_col, need_obj, need_ref,
            )
        )
        # remap pred srcs through the compaction
        new_idx = np.cumsum(valid) - 1
        if len(pr_src):
            pk = valid[pr_src]
            pr_src = new_idx[pr_src[pk]]
            pr_tgt_ctr = pr_tgt_ctr[pk]
            pr_tgt_a = pr_tgt_a[pk]
            pr_doc = pr_doc[pk]
        M = len(action)
        if M == 0:
            N = n_rows if n_rows is not None else 1
            P = n_pred if n_pred is not None else 1
            return _empty_batch(
                Dp, N, P, sorted_actors, key_int, str_int, float_int,
                big_int,
            )
        rk = _rowkey(doc_col, ctr, actor_g)
        order_rk = np.argsort(rk)
        rk_sorted = rk[order_rk]
        obj_tgt, obj_hit = _resolve(
            rk_sorted, order_rk, doc_col, obj_ctr, obj_a_g
        )
        ref_tgt, ref_hit = _resolve(
            rk_sorted, order_rk, doc_col, ref_ctr, ref_a_g
        )

    # -- causal order + within-doc positions ---------------------------
    sort_key = _rowkey(doc_col, start_op, actor_g)
    perm = np.argsort(sort_key, kind="stable")
    inv = np.empty(M, np.int64)
    inv[perm] = np.arange(M, dtype=np.int64)
    doc_counts = np.bincount(doc_col, minlength=Dp).astype(np.int64)
    doc_starts = np.zeros(Dp + 1, np.int64)
    np.cumsum(doc_counts, out=doc_starts[1:])
    pos = inv - doc_starts[doc_col]

    obj_row = np.where(need_obj, pos[obj_tgt], OBJ_ROOT)
    ref_row = np.where(
        need_ref,
        pos[ref_tgt],
        np.where(ref_a_l_compact(ref_a_g) == REF_HEAD, REF_HEAD, REF_NONE),
    )

    # -- pred edges -> per-doc rows ------------------------------------
    if len(pr_src):
        tgt_row, tgt_hit = _resolve(
            rk_sorted, order_rk, pr_doc, pr_tgt_ctr, pr_tgt_a
        )
        pk = tgt_hit
        pr_doc = pr_doc[pk]
        p_src_row = pos[pr_src[pk]]
        p_tgt_row = pos[tgt_row[pk]]
        pred_counts = np.bincount(pr_doc, minlength=Dp).astype(np.int64)
        pred_starts = np.zeros(Dp + 1, np.int64)
        np.cumsum(pred_counts, out=pred_starts[1:])
        # pr_doc is nondecreasing (windows gathered doc-by-doc; the
        # validity compaction preserves order)
        p_pos = np.arange(len(pr_doc), dtype=np.int64) - pred_starts[pr_doc]
    else:
        pred_counts = np.zeros(Dp, np.int64)
        p_src_row = p_tgt_row = p_pos = pr_doc = np.zeros(0, np.int64)

    # -- scatter into padded [D, N] ------------------------------------
    max_ops = int(doc_counts.max(initial=0))
    max_preds = int(pred_counts.max(initial=0))
    N = n_rows if n_rows is not None else _round_up(max(max_ops, 1))
    P = n_pred if n_pred is not None else _round_up(max(max_preds, 1))
    if max_ops > N or max_preds > P:
        raise ValueError(
            f"doc exceeds bucket: ops {max_ops}>{N} or preds {max_preds}>{P}"
        )

    flat_idx = doc_col * N + pos
    cols: Dict[str, np.ndarray] = {}
    defaults = {
        "action": PAD, "obj": -1, "key": -1, "ref": -3,
    }
    sources = {
        "action": action, "actor": actor_g, "ctr": ctr, "seq": seqc,
        "obj": obj_row, "key": key_g, "ref": ref_row, "insert": insert,
        "vkind": vkind, "value": value_g, "dt": dt,
    }
    for name in COLUMNS:
        flat = np.full(Dp * N, defaults.get(name, 0), np.int32)
        flat[flat_idx] = sources[name].astype(np.int32)
        cols[name] = flat.reshape(Dp, N)
    psrc = np.full(Dp * P, -1, np.int32)
    ptgt = np.full(Dp * P, -1, np.int32)
    if len(p_src_row):
        pidx = pr_doc * P + p_pos
        psrc[pidx] = p_src_row.astype(np.int32)
        ptgt[pidx] = p_tgt_row.astype(np.int32)

    # per-doc local actor map (ascending == string sort order: actor_g
    # indexes sorted_actors)
    doc_actors = doc_actor_map_from_pairs(
        np.unique(doc_col * np.int64(A) + actor_g), A, Dp
    )

    return ColumnarBatch(
        cols=cols,
        psrc=psrc.reshape(Dp, P),
        ptgt=ptgt.reshape(Dp, P),
        n_ops=doc_counts.astype(np.int32),
        actors=list(sorted_actors),
        keys=list(key_int.items),
        strings=list(str_int.items),
        floats=list(float_int.items),
        bigints=list(big_int.items),
        doc_actors=doc_actors,
    )


def ref_a_l_compact(ref_a_g: np.ndarray) -> np.ndarray:
    """Sentinels (-2 HEAD / -3 none) pass through the global remap
    unchanged; this just names that fact at the use site."""
    return ref_a_g


def _empty_batch(
    D: int, N: int, P: int, actors, key_int, str_int, float_int, big_int
) -> ColumnarBatch:
    cols = {name: np.zeros((D, N), np.int32) for name in COLUMNS}
    cols["action"][:] = PAD
    cols["obj"][:] = -1
    cols["key"][:] = -1
    cols["ref"][:] = -3
    return ColumnarBatch(
        cols=cols,
        psrc=np.full((D, P), -1, np.int32),
        ptgt=np.full((D, P), -1, np.int32),
        n_ops=np.zeros(D, np.int32),
        actors=list(actors),
        keys=list(key_int.items),
        strings=list(str_int.items),
        floats=list(float_int.items),
        bigints=list(big_int.items),
        doc_actors=np.full((D, 1), -1, np.int32),
    )


# ---------------------------------------------------------------------------
# appendable per-doc packed columns (the live apply engine's cache)


class LiveColumns:
    """ONE document's packed op history, appendable in place.

    The live apply engine (backend/live.py) keeps each hot doc's packed
    columns host-pinned: incoming changes append rows at the tail (no
    feed IO, no repack of the prefix), and each tick stacks dirty docs'
    columns into a padded [D, N] batch for the jitted kernels.

    Row encoding is `_pack_one`'s, with persistent state: `row_of`
    resolves obj/ref/pred references across appends, the interners are
    per-DOC (the kernels never read table *contents*, only group by
    index — so no batch-global remap is ever needed), and unresolvable
    ops drop exactly as `_pack_one` drops them (the OpSet tolerance).

    Row order is arrival order, NOT the causal linear order `pack_docs`
    emits. The kernels are row-order-independent (winners come from
    lexsorts over (group, lamport) keys, RGA order from explicit parent
    pointers), so appending at the tail is always sound; only consumers
    that assume causally-sorted rows (none on the live path) may not
    read these columns.

    Actor column values are intern indices; `slots()` maps them through
    the string-sort rank LUT the kernels tie-break by (recomputed only
    when a new actor joins).
    """

    _INIT_CAP = 64

    def __init__(self) -> None:
        self.n = 0
        self.n_preds = 0
        self.cols: Dict[str, np.ndarray] = {
            name: np.full(
                self._INIT_CAP, _COL_DEFAULTS.get(name, 0), np.int32
            )
            for name in COLUMNS
        }
        self.psrc = np.full(self._INIT_CAP, -1, np.int32)
        self.ptgt = np.full(self._INIT_CAP, -1, np.int32)
        self.actors = _Interner()
        self.keys = _Interner()
        self.strings = _Interner()
        self.floats = _Interner()
        self.bigints = _Interner()
        self.row_of: Dict[OpId, int] = {}
        self.opids: List[OpId] = []  # row -> OpId (append-only, so the
        # per-tick decoders reuse it instead of rebuilding O(n) objects)
        self._rank_lut: Optional[np.ndarray] = None

    @classmethod
    def from_batch(cls, batch: ColumnarBatch, d: int = 0) -> "LiveColumns":
        """Adopt one doc's rows out of a packed batch (bulk-loaded docs
        enter the live engine through this — their history is already
        packed, so adoption is a column copy plus the row_of index)."""
        lv = cls()
        n = int(batch.n_ops[d])
        lv._reserve_rows(n)
        for name in COLUMNS:
            lv.cols[name][:n] = batch.cols[name][d, :n]
        lv.n = n
        keep = np.asarray(batch.psrc[d]) >= 0
        srcs = np.asarray(batch.psrc[d])[keep].astype(np.int32)
        tgts = np.asarray(batch.ptgt[d])[keep].astype(np.int32)
        lv._reserve_preds(len(srcs))
        lv.psrc[: len(srcs)] = srcs
        lv.ptgt[: len(tgts)] = tgts
        lv.n_preds = len(srcs)
        for a in batch.actors:
            lv.actors(a)
        for k in batch.keys:
            lv.keys(k)
        for s in batch.strings:
            lv.strings(s)
        for f in batch.floats:
            lv.floats(f)
        for b in batch.bigints:
            lv.bigints(b)
        ctr = batch.cols["ctr"][d, :n].tolist()
        acts = batch.cols["actor"][d, :n]
        actors = batch.actors
        if n and int(acts.min()) == int(acts.max()):
            # single-writer doc (the dominant bulk shape): one actor
            # lookup for the whole column
            writer = actors[int(acts[0])]
            lv.opids = [OpId(c, writer) for c in ctr]
        else:
            names = [actors[a] for a in acts.tolist()]
            lv.opids = list(map(OpId, ctr, names))
        lv.row_of = dict(zip(lv.opids, range(n)))
        return lv

    # -- appends --------------------------------------------------------

    def append_changes(self, changes: Sequence[Change]) -> None:
        """Append already-admitted changes (caller enforces causal
        order + dedup — the live engine's admission mirror of OpSet)."""
        for change in changes:
            self._append_one(change)

    def _append_one(self, change: Change) -> None:
        row_of = self.row_of
        for i, op in enumerate(change.ops):
            opid = change.op_id(i)
            n_actors = len(self.actors.items)
            enc = _encode_op_row(
                op, opid, change, row_of,
                self.actors, self.keys, self.strings, self.floats,
                self.bigints,
            )
            if enc is None:
                continue
            if len(self.actors.items) != n_actors:
                self._rank_lut = None  # new actor: ranks shift
            vals, pred_tgts = enc
            row = self.n
            self._reserve_rows(row + 1)
            c = self.cols
            for name in COLUMNS:
                c[name][row] = vals[name]
            for tgt in pred_tgts:
                k = self.n_preds
                self._reserve_preds(k + 1)
                self.psrc[k] = row
                self.ptgt[k] = tgt
                self.n_preds = k + 1
            row_of[opid] = row
            self.opids.append(opid)
            self.n = row + 1

    def _reserve_rows(self, n: int) -> None:
        cap = len(self.cols["action"])
        if n <= cap:
            return
        new_cap = round_up_pow2(n)
        for name in COLUMNS:
            grown = np.full(
                new_cap, _COL_DEFAULTS.get(name, 0), np.int32
            )
            grown[: self.n] = self.cols[name][: self.n]
            self.cols[name] = grown

    def _reserve_preds(self, n: int) -> None:
        cap = len(self.psrc)
        if n <= cap:
            return
        new_cap = round_up_pow2(n)
        for attr in ("psrc", "ptgt"):
            grown = np.full(new_cap, -1, np.int32)
            grown[: self.n_preds] = getattr(self, attr)[: self.n_preds]
            setattr(self, attr, grown)

    # -- kernel views ---------------------------------------------------

    @property
    def actor_rank(self) -> np.ndarray:
        """LUT: actor intern index -> string-sort rank (the kernel's
        tie-break order)."""
        if self._rank_lut is None or len(self._rank_lut) != max(
            1, len(self.actors.items)
        ):
            order = sorted(
                range(len(self.actors.items)),
                key=lambda i: self.actors.items[i],
            )
            lut = np.zeros(max(1, len(self.actors.items)), np.int32)
            for rank, idx in enumerate(order):
                lut[idx] = rank
            self._rank_lut = lut
        return self._rank_lut

    def slots(self) -> np.ndarray:
        """[n] int32 actor slots in string-sort rank order."""
        return self.actor_rank[self.cols["actor"][: self.n]]

    def opid(self, row: int) -> OpId:
        return OpId(
            int(self.cols["ctr"][row]),
            self.actors.items[int(self.cols["actor"][row])],
        )

    def decode_row_value(self, row: int) -> Any:
        return decode_live_value(
            int(self.cols["vkind"][row]),
            int(self.cols["value"][row]),
            self,
        )

    def decode_values(self, rows: np.ndarray) -> List[Any]:
        """Decoded Python values for the given row indices — the batch
        twin of `decode_row_value`, vectorized by value kind (one
        nonzero + one tight fixup pass per kind present instead of a
        per-row Python call). The live decode's value hot path."""
        vk = self.cols["vkind"][rows]
        out: List[Any] = self.cols["value"][rows].tolist()
        if not out:
            return out
        # VK_INT rows are already right (tolist yields Python ints);
        # patch the other kinds in place
        m = vk == VK_NONE
        if m.any():
            for i in np.nonzero(m)[0].tolist():
                out[i] = None
        m = vk == VK_BOOL
        if m.any():
            for i in np.nonzero(m)[0].tolist():
                out[i] = bool(out[i])
        for code, table in (
            (VK_FLOAT, self.floats.items),
            (VK_STR, self.strings.items),
            (VK_BIGINT, self.bigints.items),
        ):
            m = vk == code
            if m.any():
                for i in np.nonzero(m)[0].tolist():
                    out[i] = table[out[i]]
        return out

    @property
    def nbytes(self) -> int:
        """Resident host bytes of this doc's live cache: the packed
        numpy planes plus an estimate of the opids/row_of index
        structures (~one OpId tuple + two dict/list slots per row).
        What the live engine's byte-bounded LRU charges a hot doc."""
        b = self.psrc.nbytes + self.ptgt.nbytes
        for a in self.cols.values():
            b += a.nbytes
        return b + len(self.opids) * 144


_COL_DEFAULTS = {"action": PAD, "obj": -1, "key": -1, "ref": -3}


def decode_live_value(vkind: int, value: int, lv: "LiveColumns") -> Any:
    if vkind == VK_NONE:
        return None
    if vkind == VK_INT:
        return int(value)
    if vkind == VK_BOOL:
        return bool(value)
    if vkind == VK_FLOAT:
        return lv.floats.items[value]
    if vkind == VK_STR:
        return lv.strings.items[value]
    if vkind == VK_BIGINT:
        return lv.bigints.items[value]
    raise ValueError(f"bad vkind {vkind}")


def decode_value(
    vkind: int, value: int, dt: int, batch: ColumnarBatch
) -> Any:
    if vkind == VK_NONE:
        return None
    if vkind == VK_INT:
        return int(value)
    if vkind == VK_BOOL:
        return bool(value)
    if vkind == VK_FLOAT:
        return batch.floats[value]
    if vkind == VK_STR:
        return batch.strings[value]
    if vkind == VK_BIGINT:
        return batch.bigints[value]
    raise ValueError(f"bad vkind {vkind}")
