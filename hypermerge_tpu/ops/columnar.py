"""Columnar op-log encoding — changes as padded int32 tensors.

The bulk half of the dual-path design (SURVEY.md §7.1, BASELINE.json):
a document's change history becomes fixed-shape int32 columns that the
device kernels (ops/crdt_kernels.py) consume; `vmap` batches documents on a
leading axis; `pjit` shards that axis over the mesh (parallel/).

Row = one op, in a causal linear order (sorted by (start_op ctr, actor) —
valid because a change depending on another always has a larger start_op).

Columns (all int32, shape [N] per doc, padded with PAD rows):
  action  Action code (change.Action; PAD=7)
  actor   index into the batch actor table
  ctr     lamport counter (op id = (ctr, actor))
  seq     change seq the op belongs to (for device clock derivation)
  obj     row index of the container's MAKE op; -1 = root map
  key     index into the batch key-string table; -1 = none (list ops)
  ref     row index: INS -> predecessor elem row (-2 = HEAD);
          SET/DEL on elem -> elem row; INC -> target value-op row; else -3
  insert  1 if the op creates a new list/text element
  vkind   value encoding kind (VK_*)
  value   inline small int / bool / index into a side table
  dt      datatype code: 0 none, 1 counter, 2 timestamp

Supersession (pred) edges are their own arrays [P]: psrc (superseding row),
ptgt (superseded row), padded with (-1, -1). INC ops contribute NO pred
edges — their target rides the ref column (an INC must not kill its
counter).

Side tables (batch-global, host-side): actors, key strings, value strings,
floats (float64 — no precision loss through the device path), bigints.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..crdt.change import HEAD, ROOT, Action, Change, OpId

PAD = int(Action.PAD)

# value kinds
VK_NONE = 0
VK_INT = 1  # inline int32
VK_FLOAT = 2  # index into floats table
VK_STR = 3  # index into strings table
VK_BOOL = 4  # inline 0/1
VK_BIGINT = 5  # index into bigints table
# MAKE_* rows carry no value (the op id is the object id)

_INT32_MIN, _INT32_MAX = -(2**31), 2**31 - 1

COLUMNS = (
    "action",
    "actor",
    "ctr",
    "seq",
    "obj",
    "key",
    "ref",
    "insert",
    "vkind",
    "value",
    "dt",
)


class _Interner:
    def __init__(self) -> None:
        self.items: List[Any] = []
        self._index: Dict[Any, int] = {}

    def __call__(self, item: Any) -> int:
        idx = self._index.get(item)
        if idx is None:
            idx = len(self.items)
            self.items.append(item)
            self._index[item] = idx
        return idx

    def __len__(self) -> int:
        return len(self.items)


@dataclass
class ColumnarBatch:
    """[D, N] padded op columns + [D, P] pred edges + side tables."""

    cols: Dict[str, np.ndarray]
    psrc: np.ndarray
    ptgt: np.ndarray
    n_ops: np.ndarray  # [D] real (unpadded) op counts
    actors: List[str]
    keys: List[str]
    strings: List[str]
    floats: List[float]
    bigints: List[int]
    op_actor_ids: List[List[str]] = field(default_factory=list)

    @property
    def shape(self) -> Tuple[int, int]:
        return self.cols["action"].shape  # (D, N)

    @property
    def n_docs(self) -> int:
        return self.shape[0]

    @property
    def n_rows(self) -> int:
        return self.shape[1]


def causal_sort(changes: Sequence[Change]) -> List[Change]:
    """Deduplicate by (actor, seq) and sort into a causal linear order.

    (start_op, actor) is a valid linear extension: if X depends on Y then
    X.start_op > Y.max_op >= Y.start_op (lamport assignment in
    OpSet.apply_local_request)."""
    seen = {}
    for c in changes:
        seen.setdefault((c.actor, c.seq), c)
    return sorted(seen.values(), key=lambda c: (c.start_op, c.actor))


def pack_docs(
    docs_changes: Sequence[Sequence[Change]],
    n_rows: Optional[int] = None,
    n_pred: Optional[int] = None,
) -> ColumnarBatch:
    """Pack many documents' histories into one padded batch."""
    actor_ids = _Interner()
    key_ids = _Interner()
    str_ids = _Interner()
    float_ids = _Interner()
    big_ids = _Interner()

    per_doc: List[Tuple[Dict[str, List[int]], List[Tuple[int, int]]]] = []
    for changes in docs_changes:
        per_doc.append(
            _pack_one(
                causal_sort(changes), actor_ids, key_ids, str_ids, float_ids,
                big_ids,
            )
        )

    # Device kernels tie-break concurrent ops by actor *index* (the
    # composite ctr*A + actor); the host OpSet tie-breaks by actor *string*
    # (OpId ordering). Remap indices so index order == string sort order.
    sorted_actors = sorted(actor_ids.items)
    lut = np.zeros(max(len(actor_ids.items), 1), dtype=np.int32)
    for old, name in enumerate(actor_ids.items):
        lut[old] = sorted_actors.index(name)
    for doc_cols, _ in per_doc:
        doc_cols["actor"] = [int(lut[a]) for a in doc_cols["actor"]]
    actor_ids.items = sorted_actors

    max_ops = max((len(d[0]["action"]) for d in per_doc), default=0)
    max_preds = max((len(d[1]) for d in per_doc), default=0)
    N = n_rows if n_rows is not None else _round_up(max(max_ops, 1))
    P = n_pred if n_pred is not None else _round_up(max(max_preds, 1))
    if max_ops > N or max_preds > P:
        raise ValueError(
            f"doc exceeds bucket: ops {max_ops}>{N} or preds {max_preds}>{P}"
        )

    D = len(per_doc)
    cols = {name: np.full((D, N), 0, dtype=np.int32) for name in COLUMNS}
    cols["action"][:] = PAD
    cols["obj"][:] = -1
    cols["key"][:] = -1
    cols["ref"][:] = -3
    psrc = np.full((D, P), -1, dtype=np.int32)
    ptgt = np.full((D, P), -1, dtype=np.int32)
    n_ops = np.zeros((D,), dtype=np.int32)

    for d, (doc_cols, preds) in enumerate(per_doc):
        n = len(doc_cols["action"])
        n_ops[d] = n
        for name in COLUMNS:
            cols[name][d, :n] = doc_cols[name]
        for k, (s, t) in enumerate(preds):
            psrc[d, k] = s
            ptgt[d, k] = t

    return ColumnarBatch(
        cols=cols,
        psrc=psrc,
        ptgt=ptgt,
        n_ops=n_ops,
        actors=list(actor_ids.items),
        keys=list(key_ids.items),
        strings=list(str_ids.items),
        floats=list(float_ids.items),
        bigints=list(big_ids.items),
    )


def _round_up(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def _pack_one(
    changes: List[Change],
    actor_ids: _Interner,
    key_ids: _Interner,
    str_ids: _Interner,
    float_ids: _Interner,
    big_ids: _Interner,
) -> Tuple[Dict[str, List[int]], List[Tuple[int, int]]]:
    cols: Dict[str, List[int]] = {name: [] for name in COLUMNS}
    preds: List[Tuple[int, int]] = []
    row_of: Dict[OpId, int] = {}
    row = 0
    for change in changes:
        for i, op in enumerate(change.ops):
            opid = change.op_id(i)
            if op.obj == ROOT:
                obj_row = -1
            else:
                obj_row = row_of.get(op.obj, -4)
                if obj_row == -4:
                    continue  # container unknown (tolerate, like OpSet)
            if op.action == Action.INC:
                target = op.pred[0] if op.pred else None
                ref_row = row_of.get(target, -3) if target else -3
                if ref_row == -3:
                    continue
            elif op.ref is None:
                ref_row = -3
            elif op.ref == HEAD:
                ref_row = -2
            else:
                ref_row = row_of.get(op.ref, -4)
                if ref_row == -4:
                    continue  # unknown element
            vkind, value = _encode_value(
                op, str_ids, float_ids, big_ids
            )
            cols["action"].append(int(op.action))
            cols["actor"].append(actor_ids(change.actor))
            cols["ctr"].append(opid.ctr)
            cols["seq"].append(change.seq)
            cols["obj"].append(obj_row)
            cols["key"].append(key_ids(op.key) if op.key is not None else -1)
            cols["ref"].append(ref_row)
            cols["insert"].append(1 if op.insert else 0)
            cols["vkind"].append(vkind)
            cols["value"].append(value)
            cols["dt"].append(
                1 if op.datatype == "counter"
                else 2 if op.datatype == "timestamp" else 0
            )
            if op.action != Action.INC:
                for p in op.pred:
                    tgt = row_of.get(p)
                    if tgt is not None:
                        preds.append((row, tgt))
            row_of[opid] = row
            row += 1
    return cols, preds


def _encode_value(op, str_ids, float_ids, big_ids) -> Tuple[int, int]:
    v = op.value
    if op.action.makes_object or v is None:
        return VK_NONE, 0
    if isinstance(v, bool):
        return VK_BOOL, 1 if v else 0
    if isinstance(v, int):
        if _INT32_MIN <= v <= _INT32_MAX:
            return VK_INT, v
        return VK_BIGINT, big_ids(v)
    if isinstance(v, float):
        return VK_FLOAT, float_ids(v)
    if isinstance(v, str):
        return VK_STR, str_ids(v)
    # fallthrough: non-scalar payloads shouldn't occur (containers are MAKE
    # ops); encode their repr so nothing crashes
    return VK_STR, str_ids(repr(v))


def decode_value(
    vkind: int, value: int, dt: int, batch: ColumnarBatch
) -> Any:
    if vkind == VK_NONE:
        return None
    if vkind == VK_INT:
        return int(value)
    if vkind == VK_BOOL:
        return bool(value)
    if vkind == VK_FLOAT:
        return batch.floats[value]
    if vkind == VK_STR:
        return batch.strings[value]
    if vkind == VK_BIGINT:
        return batch.bigints[value]
    raise ValueError(f"bad vkind {vkind}")
