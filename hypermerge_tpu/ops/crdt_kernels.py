"""Batched CRDT materialization kernels — the hot loop on device.

Computes, for a whole batch of documents at once, everything Automerge's
`Backend.applyChanges` full-replay produces (the reference's cold-start hot
loop, SURVEY.md §3.3), as one fused XLA program over the columnar encoding
(ops/columnar.py):

1. supersession: pred edges scatter a `dead` mask (observed-remove)
2. counter totals: INC deltas segment-sum onto live counter ops
3. LWW map winners: lexsort by (group, lamport) + run boundaries
4. RGA element order: one forest over all list/text objects — sibling sort
   (parent asc, OpId desc), preorder-successor via pointer-doubling climb,
   Wyllie list-ranking for positions. All data-dependent chasing is
   log2(N) rounds of gathers — no scalar loops, TPU/XLA friendly.
5. element liveness + winner value op per element (scatter-max)
6. per-doc vector clock (scatter-max of seq per actor)

Everything is `vmap`ed over the leading doc axis and jit-cached per
(N, P, A, K) bucket. The doc axis is the `dp` sharding axis (parallel/).
"""

from __future__ import annotations

import math
import os
from functools import partial
from typing import Dict, NamedTuple

import jax
import jax.numpy as jnp


from ..crdt.change import Action
from .columnar import (
    PAD,
    ColumnarBatch,
    doc_actor_map_from_pairs,
    round_up_pow2,
)

_cache_checked = False


def _enable_persistent_compile_cache() -> None:
    """Cold processes reuse warm processes' XLA executables: with stable
    jit buckets (A_loc/K bucketing below + slab-shape padding in the bulk
    loader) a second-process bulk load skips the ~25s kernel compile
    entirely. HM_COMPILE_CACHE overrides the location; empty disables.
    CPU backends are excluded: compiles there are fast and XLA:CPU AOT
    reload warns about machine-feature mismatches."""
    global _cache_checked
    if _cache_checked:
        return
    _cache_checked = True
    d = os.environ.get(
        "HM_COMPILE_CACHE",
        os.path.join(
            os.path.expanduser("~"), ".cache", "hypermerge_tpu", "xla"
        ),
    )
    force = os.environ.get("HM_COMPILE_CACHE_FORCE", "0") == "1"
    if not d or (jax.default_backend() == "cpu" and not force):
        return
    try:
        jax.config.update("jax_compilation_cache_dir", d)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs",
            0.0 if force else 0.2,
        )
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:  # unknown flags on an older jax: feature off
        pass



_SET = int(Action.SET)
_DEL = int(Action.DEL)
_INC = int(Action.INC)
_MAKE_LIST = int(Action.MAKE_LIST)
_MAKE_TEXT = int(Action.MAKE_TEXT)


class MaterializeOut(NamedTuple):
    """Per-row outputs, shape [D, N] unless noted."""

    dead: jax.Array  # bool: superseded by some pred edge
    visible: jax.Array  # bool: value op (SET/MAKE) still visible
    map_winner: jax.Array  # bool: the winning visible op of its (obj, key)
    elem_winner: jax.Array  # bool: winning visible value op of its element
    elem_live: jax.Array  # bool (INS rows): element has a visible value
    rank: jax.Array  # int32: RGA order key (higher = earlier in list)
    inc_total: jax.Array  # int32: accumulated INC deltas per value op
    clock: jax.Array  # [D, A] int32 vector clock


def _ceil_log2(n: int) -> int:
    return max(1, math.ceil(math.log2(max(n, 2))))


def _doc_kernel(
    action, slot, ctr, seq, obj, key, ref, insert, value, psrc, ptgt,
    doc_actors, *, A: int, K: int,
):
    """One document. `slot` holds per-doc LOCAL actor slots (precomputed
    on host — ensure_slot); ascending `doc_actors` [A] maps slots back to
    batch-global actor ids. A = A_loc, the per-doc actor bucket — a small
    constant independent of how many docs (and therefore distinct actors)
    share the batch, so the jit cache key and the [A] clock output don't
    scale with slab size. Slot order == actor-string sort order, the OpId
    tie-break order within this doc."""
    N = action.shape[0]
    idx = jnp.arange(N, dtype=jnp.int32)
    valid = action != PAD
    is_make = (action <= 3) & valid
    is_set = (action == _SET) & valid
    is_ins = (insert == 1) & valid

    # -- 1. supersession ------------------------------------------------
    tgt = jnp.where(ptgt >= 0, ptgt, N)
    dead = jnp.zeros(N + 1, dtype=bool).at[tgt].set(True)[:N]
    visible = (is_make | is_set) & ~dead

    # -- 2. counter increments -----------------------------------------
    is_inc = (action == _INC) & valid
    inc_tgt = jnp.clip(ref, 0, N - 1)
    inc_ok = is_inc & (ref >= 0) & ~dead[inc_tgt]
    inc_total = (
        jnp.zeros(N + 1, dtype=jnp.int32)
        .at[jnp.where(inc_ok, inc_tgt, N)]
        .add(jnp.where(inc_ok, value, 0))[:N]
    )

    # -- 3. LWW map winners --------------------------------------------
    # group id over (obj, key); 0 = not a map-located value op
    in_map = visible & (key >= 0)
    gid = jnp.where(in_map, (obj + 1) * (K + 1) + (key + 1), 0)
    order = jnp.lexsort((slot, ctr, gid))
    g_sorted = gid[order]
    run_end = jnp.concatenate(
        [g_sorted[1:] != g_sorted[:-1], jnp.ones((1,), dtype=bool)]
    )
    winner_sorted = run_end & (g_sorted > 0)
    map_winner = jnp.zeros(N, dtype=bool).at[order].set(winner_sorted)

    # -- 4. element values: winner per element -------------------------
    # OpId composite; +1 so 0 means "no visible value"
    comp = ctr * jnp.int32(A) + slot + 1
    is_elem_update = visible & ~is_ins & (key < 0) & (ref >= 0)
    own_value = visible & is_ins
    contrib = is_elem_update | own_value
    elem_of = jnp.where(is_elem_update, ref, jnp.where(own_value, idx, N))
    best = (
        jnp.zeros(N + 1, dtype=jnp.int32)
        .at[elem_of]
        .max(jnp.where(contrib, comp, 0))[:N]
    )
    elem_live = is_ins & (best > 0)
    elem_winner = contrib & (
        comp == best[jnp.clip(elem_of, 0, N - 1)]
    )

    # -- 5. RGA forest order -------------------------------------------
    is_seq_container = ((action == _MAKE_LIST) | (action == _MAKE_TEXT)) & valid
    in_forest = is_ins | is_seq_container
    # parent: INS -> predecessor elem (HEAD -> the container row);
    # non-inserted containers are tree roots (-1)
    parent = jnp.where(
        is_ins, jnp.where(ref == -2, obj, ref), jnp.int32(-1)
    )
    # sibling sort: group by parent (asc), OpId descending within group
    pa = jnp.where(in_forest, parent + 1, N + 1)
    inv = jnp.int32(2**30) - comp
    order2 = jnp.lexsort((inv, pa))
    pa_s = pa[order2]
    run_start = jnp.concatenate(
        [jnp.ones((1,), dtype=bool), pa_s[1:] != pa_s[:-1]]
    )
    fc_table = (
        jnp.full(N + 2, -1, dtype=jnp.int32)
        .at[jnp.where(run_start, pa_s, N + 1)]
        .set(jnp.where(run_start, order2, -1).astype(jnp.int32))
    )
    first_child = fc_table[idx + 1]  # children of node i have pa == i+1
    nxt_in_sort = jnp.concatenate([order2[1:], jnp.full((1,), -1, jnp.int32)])
    same_parent = jnp.concatenate(
        [pa_s[1:] == pa_s[:-1], jnp.zeros((1,), dtype=bool)]
    )
    nsib = (
        jnp.full(N, -1, dtype=jnp.int32)
        .at[order2]
        .set(jnp.where(same_parent, nxt_in_sort, -1).astype(jnp.int32))
    )

    # climb-to-sibling fixpoint via pointer doubling (terminal = N);
    # int16 payload when it fits — gathers move half the bytes
    has_sib = nsib != -1
    jump = jnp.where(
        has_sib, idx, jnp.where(parent >= 0, parent, N)
    ).astype(jnp.int32)
    jump = jnp.where(in_forest, jump, N)
    jump_ext = jnp.concatenate([jump, jnp.array([N], jnp.int32)])
    if N < 2**15:
        j16 = jump_ext.astype(jnp.int16)
        for _ in range(_ceil_log2(N) + 1):
            j16 = j16[j16.astype(jnp.int32)]
        jump_ext = j16.astype(jnp.int32)
    else:
        for _ in range(_ceil_log2(N) + 1):
            jump_ext = jump_ext[jump_ext]
    fix = jump_ext[:N]
    nsib_ext = jnp.concatenate([nsib, jnp.array([-1], jnp.int32)])
    succ = jnp.where(first_child != -1, first_child, nsib_ext[fix])
    succ = jnp.where(in_forest, succ, -1)
    nxt = jnp.where(succ == -1, N, succ).astype(jnp.int32)

    # Wyllie list-ranking: rank = #nodes from here to end of chain
    rank = jnp.where(in_forest, 1, 0).astype(jnp.int32)
    if N < 2**15:
        # pack (rank, nxt) into one int32 lane: rank <= chain length <= N
        # < 2^15 and nxt <= N, so `nxt | rank<<16` fits — one gather per
        # round instead of two (the gathers, not the VPU work, bound
        # these loops on TPU)
        p = jnp.concatenate([nxt, jnp.array([N], jnp.int32)]) | (
            jnp.concatenate([rank, jnp.zeros((1,), jnp.int32)]) << 16
        )
        for _ in range(_ceil_log2(N) + 1):
            q = p[p & 0xFFFF]
            p = (q & 0xFFFF) | ((p >> 16) + (q >> 16)) << 16
        rank = (p >> 16)[:N]
    else:
        rank_ext = jnp.concatenate([rank, jnp.zeros((1,), jnp.int32)])
        nxt_ext = jnp.concatenate([nxt, jnp.array([N], jnp.int32)])
        for _ in range(_ceil_log2(N) + 1):
            rank_ext = rank_ext + rank_ext[nxt_ext]
            nxt_ext = nxt_ext[nxt_ext]
        rank = rank_ext[:N]

    # -- 6. clock (local slots; [A_loc], decoded via doc_actors) -------
    clock = (
        jnp.zeros(A, dtype=jnp.int32)
        .at[jnp.where(valid, slot, 0)]
        .max(jnp.where(valid, seq, 0))
    )

    return MaterializeOut(
        dead=dead,
        visible=visible,
        map_winner=map_winner,
        elem_winner=elem_winner,
        elem_live=elem_live,
        rank=rank,
        inc_total=inc_total,
        clock=clock,
    )


def _widen(flags, slot, ctr, seq, obj, key, ref, value, psrc, ptgt):
    """Narrow wire dtypes -> int32 kernel lanes. The host packs columns
    as small as their ranges allow (uint8 flags = action|insert<<3,
    int8 slots, int16 rows/ids when they fit) because the host<->device
    link — not the MXU/VPU — bounds the bulk path: widening on device is
    fused VPU work, while every wire byte is wall-clock."""
    i32 = jnp.int32
    action = (flags & 7).astype(i32)
    insert = ((flags >> 3) & 1).astype(i32)
    return (
        action, slot.astype(i32), ctr.astype(i32), seq.astype(i32),
        obj.astype(i32), key.astype(i32), ref.astype(i32), insert,
        value.astype(i32), psrc.astype(i32), ptgt.astype(i32),
    )


def batched_kernel(A: int, K: int):
    """Batched (vmapped) kernel over narrow wire args — the function the
    single-device jits and the mesh-sharded path (parallel/sharded.py)
    both compile, so both lower to the same program."""

    def fn(flags, slot, ctr, seq, obj, key, ref, value, psrc, ptgt,
           doc_actors):
        (action, slot_w, ctr_w, seq_w, obj_w, key_w, ref_w, insert,
         value_w, psrc_w, ptgt_w) = _widen(
            flags, slot, ctr, seq, obj, key, ref, value, psrc, ptgt
        )
        return jax.vmap(lambda *xs: _doc_kernel(*xs, A=A, K=K))(
            action, slot_w, ctr_w, seq_w, obj_w, key_w, ref_w, insert,
            value_w, psrc_w, ptgt_w, doc_actors,
        )

    return fn


@partial(jax.jit, static_argnames=("A", "K"))
def materialize_device(
    flags, slot, ctr, seq, obj, key, ref, value, psrc, ptgt,
    doc_actors, A: int, K: int,
) -> MaterializeOut:
    """Batched kernel: all args [D, N] narrow wire dtypes (pred edges
    [D, P], actor map [D, A_loc])."""
    return batched_kernel(A, K)(
        flags, slot, ctr, seq, obj, key, ref, value, psrc, ptgt,
        doc_actors,
    )


# ---------------------------------------------------------------------------
# summary wire: ONE fused uint8 buffer per slab
#
# The materialization barrier's transfer used to be six leaves per slab
# (bit-packed masks, an int16 elem_order, two count vectors, the clock).
# Bytes — not dispatches — bound the tunneled link, and elem_order was
# ~85% of them at 16 bits per entry for values that need ceil(log2 N).
# The wire packs everything into a single [D, W] uint8 buffer per slab:
# masks bit-packed, elem_order at exactly `order_bits` bits per entry,
# counts at int16 when N allows, and the clock section omitted entirely
# on lean runs (the bulk loader holds authoritative host clocks). For
# the 10k x 1k corpus this is ~1540 bytes/doc vs ~2330 — and one
# transfer to start asynchronously instead of six.


def summary_wire_spec(N: int, A: int, lean: bool) -> Dict[str, int]:
    """Byte layout of the [D, W] summary wire buffer."""
    mask_bytes = (N + 7) // 8
    order_bits = max(1, (N - 1).bit_length())
    if order_bits > 25:
        # _unpack_uint gathers at most 4 bytes per value: shift (<=7) +
        # order_bits must fit a 32-bit window, so entries wider than 25
        # bits would decode silently truncated. No real bucket is within
        # two orders of magnitude of 2^25 rows; reject loudly.
        raise ValueError(
            f"summary wire bucket too large: N={N} needs "
            f"{order_bits}-bit order entries, max 25 (N <= 2^25)"
        )
    order_bytes = (N * order_bits + 7) // 8
    count_bytes = 2 if N < 2**15 else 4
    clock_bytes = 0 if lean else 4 * A
    return {
        "mask_bytes": mask_bytes,
        "order_bits": order_bits,
        "order_bytes": order_bytes,
        "count_bytes": count_bytes,
        "clock_bytes": clock_bytes,
        "total": 2 * mask_bytes + order_bytes + 2 * count_bytes
        + clock_bytes,
    }


def _pack_bits(mask: jax.Array) -> jax.Array:
    """[D, N] bool/0-1 -> [D, ceil(N/8)] uint8, little bit order (numpy
    np.unpackbits(..., bitorder='little') inverts it exactly)."""
    D, N = mask.shape
    pad = (-N) % 8
    m = jnp.pad(mask.astype(jnp.uint8), ((0, 0), (0, pad))).reshape(
        D, -1, 8
    )
    weights = jnp.asarray([1, 2, 4, 8, 16, 32, 64, 128], jnp.uint8)
    return (m * weights).sum(-1).astype(jnp.uint8)


def _pack_uint(vals: jax.Array, bits: int) -> jax.Array:
    """[D, N] ints in [0, 2^bits) -> [D, ceil(N*bits/8)] uint8: each
    value at exactly `bits` bits, little bit order throughout."""
    D, N = vals.shape
    shifts = jnp.arange(bits, dtype=jnp.int32)
    bitmat = (
        (vals.astype(jnp.int32)[..., None] >> shifts) & 1
    ).reshape(D, N * bits)
    return _pack_bits(bitmat)


def _le_bytes(x: jax.Array, nbytes: int) -> jax.Array:
    """[D, k] ints -> [D, k*nbytes] uint8, little-endian per element
    (portable across backends — no bitcast)."""
    xi = x.astype(jnp.int32)
    parts = [
        ((xi >> (8 * i)) & 0xFF).astype(jnp.uint8) for i in range(nbytes)
    ]
    return jnp.stack(parts, axis=-1).reshape(x.shape[0], -1)


def _summarize_wire(
    out: MaterializeOut, N: int, A: int, lean: bool
) -> jax.Array:
    spec = summary_wire_spec(N, A, lean)
    order_key = jnp.where(
        out.elem_live, -out.rank, jnp.iinfo(jnp.int32).max
    )
    elem_order = jnp.argsort(order_key, axis=1).astype(jnp.int32)
    cb = spec["count_bytes"]
    parts = [
        _pack_bits(out.map_winner),
        _pack_bits(out.elem_live),
        _pack_uint(elem_order, spec["order_bits"]),
        _le_bytes(out.elem_live.sum(axis=1, dtype=jnp.int32)[:, None], cb),
        _le_bytes(out.map_winner.sum(axis=1, dtype=jnp.int32)[:, None], cb),
    ]
    if not lean:
        parts.append(_le_bytes(out.clock, 4))
    return jnp.concatenate(parts, axis=1)


def _unpack_uint(packed: "Any", N: int, bits: int) -> "Any":
    """Host-side inverse of _pack_uint: [D, OB] uint8 -> [D, N] int64.
    Vectorized byte gathers — no np.unpackbits blowup (that would
    materialize `bits` bytes per value)."""
    import numpy as np

    D = packed.shape[0]
    idx = np.arange(N, dtype=np.int64) * bits
    lo = (idx >> 3).astype(np.int64)
    sh = (idx & 7).astype(np.int64)
    pk = np.concatenate([packed, np.zeros((D, 4), np.uint8)], axis=1)
    wide = bits > 17  # sh + bits can exceed the 3-byte window
    acct = np.int64 if wide else np.int32
    acc = pk[:, lo].astype(acct)
    acc |= pk[:, lo + 1].astype(acct) << 8
    acc |= pk[:, lo + 2].astype(acct) << 16
    if wide:
        acc |= pk[:, lo + 3].astype(acct) << 24
    return ((acc >> sh.astype(acct)) & ((1 << bits) - 1)).astype(np.int64)


def unpack_bits_le(packed, N: int):
    """Host-side inverse of _pack_bits: [D, ceil(N/8)] uint8 -> [D, N]
    bool. The single unpack twin for BOTH fetched wires and memo-served
    summary rows — bit order/padding changes happen here and in
    _pack_bits only."""
    import numpy as np

    return np.unpackbits(
        np.ascontiguousarray(packed), axis=1, bitorder="little"
    )[:, :N].astype(bool)


def parse_summary_wire(wire, N: int, A: int, lean: bool):
    """Host decode of one slab's fused summary buffer -> the columnar
    summary dict (same keys/values as ops.materialize.decode_columnar;
    the clock comes back zeros on lean wires — the caller overlays its
    authoritative host clocks)."""
    import numpy as np

    spec = summary_wire_spec(N, A, lean)
    wire = np.asarray(wire)
    D = wire.shape[0]
    assert wire.shape[1] == spec["total"], (wire.shape, spec)
    mb = spec["mask_bytes"]

    def bits(seg):
        return unpack_bits_le(seg, N)

    o = 2 * mb
    ob = spec["order_bytes"]
    elem_order = _unpack_uint(
        np.ascontiguousarray(wire[:, o : o + ob]), N, spec["order_bits"]
    )
    o += ob
    cb = spec["count_bytes"]
    cdt = "<i2" if cb == 2 else "<i4"
    n_live = (
        np.ascontiguousarray(wire[:, o : o + cb])
        .view(cdt)
        .ravel()
        .astype(np.int64)
    )
    o += cb
    n_map = (
        np.ascontiguousarray(wire[:, o : o + cb])
        .view(cdt)
        .ravel()
        .astype(np.int64)
    )
    o += cb
    if lean:
        clock = np.zeros((D, A), np.int32)
    else:
        clock = (
            np.ascontiguousarray(wire[:, o : o + 4 * A])
            .view("<i4")
            .reshape(D, A)
        )
    return {
        "map_winner": bits(wire[:, 0:mb]),
        "elem_live": bits(wire[:, mb : 2 * mb]),
        "elem_order": elem_order,
        "n_live_elems": n_live,
        "n_map_entries": n_map,
        "clock": clock,
    }


@partial(jax.jit, static_argnames=("A", "K"))
def materialize_summary_device(
    flags, slot, ctr, seq, obj, key, ref, value, psrc, ptgt,
    doc_actors, A: int, K: int,
) -> jax.Array:
    """Kernel + on-device summarization in ONE dispatch: the full per-row
    lanes (visible/rank/winner masks) never leave the device; the return
    is the fused summary wire buffer."""
    out = batched_kernel(A, K)(
        flags, slot, ctr, seq, obj, key, ref, value, psrc, ptgt,
        doc_actors,
    )
    return _summarize_wire(out, flags.shape[1], A, lean=False)


@partial(jax.jit, static_argnames=("A", "K"))
def materialize_full_device(
    flags, slot, ctr, seq, obj, key, ref, value, psrc, ptgt,
    doc_actors, A: int, K: int,
):
    """One dispatch -> (MaterializeOut, summary wire). The bulk loader
    uses this: the fused summary buffer transfers compactly for the
    materialization barrier, while the full lanes stay device-resident
    for lazy per-doc patch decode (DecodedBatch.doc_view)."""
    out = batched_kernel(A, K)(
        flags, slot, ctr, seq, obj, key, ref, value, psrc, ptgt,
        doc_actors,
    )
    return out, _summarize_wire(out, flags.shape[1], A, lean=False)


@partial(jax.jit, static_argnames=("A", "K"))
def materialize_full_lean_device(
    flags, slot, ctr, obj, key, ref, psrc, ptgt, doc_actors,
    A: int, K: int,
):
    """materialize_full_device minus the seq and value wires (~4 bytes/op
    on a link where every byte is wall-clock) AND minus the summary's
    clock section. Correct ONLY when the batch has no INC ops (value
    feeds counter accumulation) and the caller supplies clocks host-side
    (seq feeds only the clock lane — the bulk loader's clocks come from
    the sidecar metadata and are the more authoritative value anyway).
    inc_total and clock lanes come back as zeros."""
    zeros = jnp.zeros_like(ctr)
    out = batched_kernel(A, K)(
        flags, slot, ctr, zeros, obj, key, ref, zeros, psrc, ptgt,
        doc_actors,
    )
    return out, _summarize_wire(out, flags.shape[1], A, lean=True)


LIVE_MIN_ROWS = 64
LIVE_MIN_DOCS = 1


def live_bucket(n: int, floor: int) -> int:
    """Pow2 jit bucket with a floor: live tick batches pad their row /
    doc / actor-slot / key axes to these shapes so a stream of ticks
    reuses a handful of compiled programs instead of compiling one per
    exact shape (the same bucketing discipline as the bulk slab path)."""
    return max(floor, round_up_pow2(max(n, 1)))


@partial(jax.jit, static_argnames=("A", "K"))
def materialize_live_device(
    flags, slot, ctr, obj, key, ref, value, psrc, ptgt, A: int, K: int
) -> MaterializeOut:
    """The live tick entry: materialize_device minus the seq wire and
    the doc-actor map. The live engine holds authoritative clocks
    host-side (admission mirrors OpSet's causal gating), so the clock
    lane is never read — seq uploads nothing and the [D, A] clock
    output comes back zeros. `value` still rides the wire: live batches
    may carry INC ops."""
    _enable_persistent_compile_cache()
    zeros = jnp.zeros_like(ctr)
    da = jnp.zeros((flags.shape[0], A), jnp.int32)
    return batched_kernel(A, K)(
        flags, slot, ctr, zeros, obj, key, ref, value, psrc, ptgt, da
    )


def ensure_doc_actors(batch: ColumnarBatch):
    """batch.doc_actors, deriving it from the actor column when a legacy
    producer didn't supply one (cached back onto the batch)."""
    import numpy as np

    if batch.doc_actors is not None:
        return batch.doc_actors
    A = max(1, len(batch.actors))
    D = batch.n_docs
    valid = batch.cols["action"] != PAD
    dcol = np.repeat(np.arange(D, dtype=np.int64), batch.n_rows)
    acol = batch.cols["actor"].astype(np.int64).ravel()
    pairs = np.unique((dcol * A + acol)[valid.ravel()])
    batch.doc_actors = doc_actor_map_from_pairs(pairs, A, D)
    return batch.doc_actors


def bucket_doc_actors(batch: ColumnarBatch):
    """(doc_actors padded to the A_loc bucket, A_loc, K): the pow2 bucket
    shape (A_loc >= 4, K >= 16) shared by the single-device and sharded
    paths so batches of different composition land in the same compiled
    program — a bulk load's slabs all reuse one executable."""
    import numpy as np

    da = ensure_doc_actors(batch)
    A = max(4, round_up_pow2(da.shape[1]))
    if da.shape[1] < A:
        da = np.concatenate(
            [da, np.full((da.shape[0], A - da.shape[1]), -1, np.int32)],
            axis=1,
        )
    K = max(16, round_up_pow2(max(1, len(batch.keys))))
    return da, A, K


def ensure_slot(batch: ColumnarBatch):
    """[D, N] per-doc LOCAL actor slot per row (int16), derived from the
    global actor column + doc_actors map and cached on the batch. One
    vectorized searchsorted — rows of doc_actors are ascending, so a
    doc-offset composite keeps the flat array sorted."""
    import numpy as np

    if batch.slot is not None:
        return batch.slot
    da = ensure_doc_actors(batch)
    D, A = da.shape
    stride = max(2, len(batch.actors) + 2)
    docs = np.arange(D, dtype=np.int64)[:, None]
    flat_da = np.where(
        da < 0, stride - 1, da.astype(np.int64)
    ) + docs * stride
    comp = batch.cols["actor"].astype(np.int64) + docs * stride
    slot = (
        np.searchsorted(flat_da.ravel(), comp.ravel())
        - (np.repeat(np.arange(D, dtype=np.int64), batch.n_rows) * A)
    )
    # PAD rows may name an actor outside the doc's set; clamp into [0, A)
    batch.slot = np.clip(slot, 0, A - 1).astype(np.int16).reshape(D, -1)
    return batch.slot


def _narrow(arr, lo: int, hi: int):
    """Smallest safe wire dtype for values known to lie in [lo, hi]."""
    import numpy as np

    if lo >= -(2**15) and hi < 2**15:
        return np.ascontiguousarray(arr, dtype=np.int16)
    return np.ascontiguousarray(arr, dtype=np.int32)


def host_args(batch: ColumnarBatch, lean: bool = False):
    """(numpy wire args, A_loc, K): the narrow columns every kernel entry
    transfers. uint8 flags = action|insert<<3; int8 slot; int16 where the
    value range fits (N-indexed columns whenever N < 32k — the common
    case), int32 otherwise. Dtypes are a function of the (N, P) bucket
    and value ranges, so slabs of one bulk load share one executable.
    `lean` leaves the seq/value slots as None — their narrowing passes
    (two [D, N] copies + range scans) are skipped, not just their
    uploads."""
    import numpy as np

    da, A, K = bucket_doc_actors(batch)
    slot = ensure_slot(batch)
    c = batch.cols
    _check_ranges(batch, A, K)
    N = batch.n_rows
    flags = (
        np.asarray(c["action"], np.uint8)
        | (np.asarray(c["insert"], np.uint8) << 3)
    )
    cmax = int(c["ctr"].max(initial=0))
    if lean:
        seq_w = value_w = None
    else:
        vmax = int(c["value"].max(initial=0))
        vmin = int(c["value"].min(initial=0))
        smax = int(c["seq"].max(initial=0))
        seq_w = _narrow(c["seq"], 0, smax)
        value_w = _narrow(c["value"], vmin, vmax)
    args = (
        flags,
        np.ascontiguousarray(
            slot, dtype=np.int8 if A <= 127 else np.int16
        ),
        _narrow(c["ctr"], 0, cmax),
        seq_w,
        _narrow(c["obj"], -1, N - 1),
        _narrow(c["key"], -1, max(0, len(batch.keys) - 1)),
        _narrow(c["ref"], -3, N - 1),
        value_w,
        _narrow(batch.psrc, -1, N - 1),
        _narrow(batch.ptgt, -1, N - 1),
        np.ascontiguousarray(da, np.int32),
    )
    return args, A, K


# stage timings of the most recent _device_args call — the bulk loader
# folds these into last_bulk_stats for the bench's stage breakdown
last_args_timings: Dict[str, float] = {}


def _device_args(batch: ColumnarBatch, lean: bool = False, device=None):
    """(device args, A_loc, K) for the jitted kernels. `lean` skips the
    seq/value builds and uploads (their slots are None). `device` pins
    the upload to a specific device (the slab round-robin scheduler);
    None uses the default placement."""
    import time

    _enable_persistent_compile_cache()
    t0 = time.perf_counter()
    np_args, A, K = host_args(batch, lean=lean)
    t1 = time.perf_counter()
    if device is None:
        args = tuple(
            None if a is None else jnp.asarray(a) for a in np_args
        )
    else:
        args = tuple(
            None if a is None else jax.device_put(a, device)
            for a in np_args
        )
    t2 = time.perf_counter()
    last_args_timings["narrow"] = t1 - t0
    last_args_timings["upload"] = t2 - t1
    return args, A, K


def run_batch_summary(batch: ColumnarBatch) -> jax.Array:
    """Host entry for the bulk path: pack numpy -> fused kernel+summary
    wire buffer (decode with parse_summary_wire)."""
    args, A, K = _device_args(batch)
    return materialize_summary_device(*args, A=A, K=K)


def run_batch(batch: ColumnarBatch) -> MaterializeOut:
    """Convenience host entry: pack numpy -> device -> outputs."""
    args, A, K = _device_args(batch)
    return materialize_device(*args, A=A, K=K)


def run_batch_full(
    batch: ColumnarBatch, lean: bool = False, device=None
):
    """Host entry -> (MaterializeOut, fused summary wire buffer) in one
    dispatch (decode the wire with parse_summary_wire).

    `lean=True` (callers that hold authoritative host clocks and verified
    the batch carries no INC ops) skips the seq/value wires entirely.
    `device` pins args (and therefore execution) to one device — the
    slab round-robin scheduler's per-chip dispatch."""
    args, A, K = _device_args(batch, lean=lean, device=device)
    if lean:
        (flags, slot, ctr, _seq, obj, key, ref, _value, psrc, ptgt,
         da) = args
        return materialize_full_lean_device(
            flags, slot, ctr, obj, key, ref, psrc, ptgt, da, A=A, K=K
        )
    return materialize_full_device(*args, A=A, K=K)


def _check_ranges(batch: ColumnarBatch, A: int, K: int) -> None:
    N = batch.n_rows
    max_ctr = int(batch.cols["ctr"].max(initial=0))
    if max_ctr * A + A >= 2**30:
        raise ValueError(
            f"lamport x actor-slot composite overflow: ctr={max_ctr} A={A}"
        )
    if (N + 1) * (K + 1) + K >= 2**31:
        raise ValueError(f"obj x key group id overflow: N={N} K={K}")
