"""Numpy twin of the device materialization kernel — the interactive path.

A single cold `repo.open` must cost milliseconds, not a device dispatch:
over the tunneled single-chip link a first-touch [1, N] program pays a
compile, which is absurd for one document. This module computes exactly
what ops/crdt_kernels._doc_kernel computes (same algorithm: supersession
scatter, INC segment-sum, LWW lexsort winners, RGA forest via pointer
doubling + Wyllie ranking, local-slot clock) with numpy only, so the
backend's sidecar-based single-doc open (repo_backend._load_document_fast)
never replays per-op host Python NOR touches the device.

Bit-equivalence with the device kernel is tested (tests/
test_device_materialize.py::test_host_kernel_matches_device).

Reference anchor: this replaces the per-change Automerge replay of
reference src/DocBackend.ts:144-167 for already-stored histories.
"""

from __future__ import annotations

import math
from typing import NamedTuple

import numpy as np

from ..crdt.change import Action
from .columnar import PAD, ColumnarBatch

_SET = int(Action.SET)
_INC = int(Action.INC)
_MAKE_LIST = int(Action.MAKE_LIST)
_MAKE_TEXT = int(Action.MAKE_TEXT)


class HostOut(NamedTuple):
    """Same lanes as crdt_kernels.MaterializeOut, numpy-backed."""

    dead: np.ndarray
    visible: np.ndarray
    map_winner: np.ndarray
    elem_winner: np.ndarray
    elem_live: np.ndarray
    rank: np.ndarray
    inc_total: np.ndarray
    clock: np.ndarray


def _ceil_log2(n: int) -> int:
    return max(1, math.ceil(math.log2(max(n, 2))))


def _host_doc_kernel(
    action, actor, ctr, seq, obj, key, ref, insert, value, psrc, ptgt,
    doc_actors, A: int, K: int,
):
    N = len(action)
    idx = np.arange(N, dtype=np.int32)
    valid = action != PAD
    is_make = (action <= 3) & valid
    is_set = (action == _SET) & valid
    is_ins = (insert == 1) & valid

    slot = np.argmax(
        actor[:, None] == doc_actors[None, :], axis=1
    ).astype(np.int32)

    # -- 1. supersession ------------------------------------------------
    tgt = np.where(ptgt >= 0, ptgt, N)
    dead_ext = np.zeros(N + 1, bool)
    dead_ext[tgt] = True
    dead = dead_ext[:N]
    visible = (is_make | is_set) & ~dead

    # -- 2. counter increments -----------------------------------------
    is_inc = (action == _INC) & valid
    inc_tgt = np.clip(ref, 0, N - 1)
    inc_ok = is_inc & (ref >= 0) & ~dead[inc_tgt]
    inc_total = np.zeros(N + 1, np.int32)
    np.add.at(
        inc_total,
        np.where(inc_ok, inc_tgt, N),
        np.where(inc_ok, value, 0),
    )
    inc_total = inc_total[:N]

    # -- 3. LWW map winners --------------------------------------------
    in_map = visible & (key >= 0)
    gid = np.where(
        in_map, (obj.astype(np.int64) + 1) * (K + 1) + (key + 1), 0
    )
    order = np.lexsort((slot, ctr, gid))
    g_sorted = gid[order]
    run_end = np.concatenate([g_sorted[1:] != g_sorted[:-1], [True]])
    winner_sorted = run_end & (g_sorted > 0)
    map_winner = np.zeros(N, bool)
    map_winner[order] = winner_sorted

    # -- 4. element values: winner per element -------------------------
    comp = ctr * np.int32(A) + slot + 1
    is_elem_update = visible & ~is_ins & (key < 0) & (ref >= 0)
    own_value = visible & is_ins
    contrib = is_elem_update | own_value
    elem_of = np.where(is_elem_update, ref, np.where(own_value, idx, N))
    best = np.zeros(N + 1, np.int32)
    np.maximum.at(best, elem_of, np.where(contrib, comp, 0))
    best = best[:N]
    elem_live = is_ins & (best > 0)
    elem_winner = contrib & (comp == best[np.clip(elem_of, 0, N - 1)])

    # -- 5. RGA forest order -------------------------------------------
    is_seq_container = (
        (action == _MAKE_LIST) | (action == _MAKE_TEXT)
    ) & valid
    in_forest = is_ins | is_seq_container
    parent = np.where(
        is_ins, np.where(ref == -2, obj, ref), np.int32(-1)
    )
    pa = np.where(in_forest, parent + 1, N + 1)
    inv = np.int32(2**30) - comp
    order2 = np.lexsort((inv, pa)).astype(np.int32)
    pa_s = pa[order2]
    run_start = np.concatenate([[True], pa_s[1:] != pa_s[:-1]])
    fc_table = np.full(N + 2, -1, np.int32)
    fc_table[np.where(run_start, pa_s, N + 1)] = np.where(
        run_start, order2, -1
    )
    first_child = fc_table[idx + 1]
    nxt_in_sort = np.concatenate([order2[1:], [np.int32(-1)]])
    same_parent = np.concatenate([pa_s[1:] == pa_s[:-1], [False]])
    nsib = np.full(N, -1, np.int32)
    nsib[order2] = np.where(same_parent, nxt_in_sort, -1)

    has_sib = nsib != -1
    jump = np.where(
        has_sib, idx, np.where(parent >= 0, parent, N)
    ).astype(np.int32)
    jump = np.where(in_forest, jump, N)
    jump_ext = np.concatenate([jump, [np.int32(N)]])
    for _ in range(_ceil_log2(N) + 1):
        jump_ext = jump_ext[jump_ext]
    fix = jump_ext[:N]
    nsib_ext = np.concatenate([nsib, [np.int32(-1)]])
    succ = np.where(first_child != -1, first_child, nsib_ext[fix])
    succ = np.where(in_forest, succ, -1)
    nxt = np.where(succ == -1, N, succ).astype(np.int32)

    rank = np.where(in_forest, 1, 0).astype(np.int32)
    rank_ext = np.concatenate([rank, [np.int32(0)]])
    nxt_ext = np.concatenate([nxt, [np.int32(N)]])
    for _ in range(_ceil_log2(N) + 1):
        rank_ext = rank_ext + rank_ext[nxt_ext]
        nxt_ext = nxt_ext[nxt_ext]
    rank = rank_ext[:N]

    # -- 6. clock -------------------------------------------------------
    clock = np.zeros(A, np.int32)
    np.maximum.at(
        clock,
        np.where(valid, slot, 0),
        np.where(valid, seq, 0),
    )

    return HostOut(
        dead=dead,
        visible=visible,
        map_winner=map_winner,
        elem_winner=elem_winner,
        elem_live=elem_live,
        rank=rank,
        inc_total=inc_total,
        clock=clock,
    )


def run_batch_host(batch: ColumnarBatch) -> HostOut:
    """The host entry: same lanes as crdt_kernels.run_batch, stacked
    [D, ...] numpy arrays. Used for small interactive loads where a
    device dispatch (and its per-bucket compile) costs more than it
    saves; bulk loads should stay on the device path."""
    from .crdt_kernels import bucket_doc_actors

    da, A, K = bucket_doc_actors(batch)
    # widen: batches may carry narrow wire dtypes (int16/uint8) whose
    # composites (ctr * A) would overflow in-place
    c = {k: np.asarray(v, np.int32) for k, v in batch.cols.items()}
    psrc = np.asarray(batch.psrc, np.int32)
    ptgt = np.asarray(batch.ptgt, np.int32)
    outs = [
        _host_doc_kernel(
            c["action"][d], c["actor"][d], c["ctr"][d], c["seq"][d],
            c["obj"][d], c["key"][d], c["ref"][d], c["insert"][d],
            c["value"][d], psrc[d], ptgt[d], da[d], A, K,
        )
        for d in range(batch.n_docs)
    ]
    return HostOut(
        *(np.stack([getattr(o, f) for o in outs]) for f in HostOut._fields)
    )
