"""Batched vector-clock algebra as XLA programs.

TPU-first re-expression of reference src/Clock.ts + the ClockStore bulk
queries (reference src/ClockStore.ts:63-72 getMultiple): clocks live as dense
`[docs, actors]` int32 matrices; cmp/gte/union/intersection become elementwise
comparisons + small reductions that XLA fuses into a single kernel; the 100k-
doc clock-union/cursor query (BASELINE.json config 5) is one device dispatch
sharded over the `dp` mesh axis (see parallel/sharded.py).

All kernels are shape-polymorphic in the leading batch dims and jit-cached.
Seqs are int32; the cursor sentinel "infinity" (reference CursorStore
INFINITY_SEQ) maps to INT32_INF on device.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

INT32_INF = jnp.int32(2**31 - 1)

# cmp result codes — stable across host/device (crdt/clock.Ordering)
EQ, GT, LT, CONCUR = 0, 1, 2, 3


@jax.jit
def gte(a: jax.Array, b: jax.Array) -> jax.Array:
    """a, b: [..., actors] -> [...] bool. a dominates b elementwise."""
    return jnp.all(a >= b, axis=-1)


@jax.jit
def cmp(a: jax.Array, b: jax.Array) -> jax.Array:
    """[..., actors] x [..., actors] -> [...] int32 code (EQ/GT/LT/CONCUR)."""
    a_gte = jnp.all(a >= b, axis=-1)
    b_gte = jnp.all(b >= a, axis=-1)
    return jnp.where(
        a_gte & b_gte,
        EQ,
        jnp.where(a_gte, GT, jnp.where(b_gte, LT, CONCUR)),
    ).astype(jnp.int32)


@jax.jit
def union(a: jax.Array, b: jax.Array) -> jax.Array:
    return jnp.maximum(a, b)


@jax.jit
def intersection(a: jax.Array, b: jax.Array) -> jax.Array:
    return jnp.minimum(a, b)


@jax.jit
def union_reduce(clocks: jax.Array) -> jax.Array:
    """[n, actors] -> [actors]: union of many clocks in one reduction —
    the ClockStore.getMultiple + Clock.union fold as a single max-reduce."""
    return jnp.max(clocks, axis=0)


@jax.jit
def satisfied(clock: jax.Array, minimum: jax.Array) -> jax.Array:
    """minimumClock render gate (reference src/DocBackend.ts:90-113):
    clock [..., actors] >= minimum [..., actors] -> [...] bool."""
    return jnp.all(clock >= minimum, axis=-1)


@jax.jit
def cursor_window(doc_seqs: jax.Array, cursor_seqs: jax.Array) -> jax.Array:
    """Change-window computation of RepoBackend.syncChanges (reference
    src/RepoBackend.ts:513-522): per (doc, actor), how many new changes the
    cursor admits beyond what the doc already holds.

    doc_seqs, cursor_seqs: [..., actors] -> [..., actors] int32 counts.
    """
    return jnp.maximum(jnp.minimum(cursor_seqs, INT32_INF) - doc_seqs, 0)


@partial(jax.jit, static_argnames=("k",))
def top_k_dominated(clocks: jax.Array, query: jax.Array, k: int):
    """Bulk query: indices of up to k docs whose clock is dominated by
    `query` — the device form of 'which docs are fully covered by this
    cursor'. clocks: [docs, actors]; query: [actors]."""
    ok = jnp.all(clocks <= query[None, :], axis=-1)
    # per-actor contributions capped so the int32 sum cannot wrap even with
    # INT32_INF sentinel entries (supports up to 2^10 actors safely)
    capped = jnp.minimum(clocks, 1 << 20)
    score = jnp.where(ok, jnp.sum(capped, axis=-1), -1)
    return jax.lax.top_k(score, k)


def pack_clocks(rows) -> jax.Array:
    """Host rows (crdt.clock.pack output) -> device array with int32 clamp."""
    import numpy as np

    arr = np.asarray(rows, dtype=np.int64)
    arr = np.minimum(arr, int(INT32_INF))
    return jnp.asarray(arr.astype(np.int32))
