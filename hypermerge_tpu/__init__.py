"""hypermerge_tpu — a TPU-native peer-to-peer CRDT document framework.

A ground-up re-design of the capabilities of hypermerge (reference:
/root/reference, a Node/TypeScript library combining an Automerge-style JSON
CRDT with hypercore-style signed append-only feeds) built TPU-first:

- The CRDT compute path — vector-clock algebra, LWW map resolution, RGA list
  ordering, whole-document materialization — runs as batched JAX/XLA programs
  (`vmap` across documents, `pjit`/`shard_map` across chips of a Mesh).
- The runtime around it — repo orchestration, per-actor append-only signed
  feeds, replication, storage — is host-side Python/C++ mirroring the
  reference's layer map (see SURVEY.md §1).

Public surface mirrors the reference facade (reference src/index.ts:1-12,
src/Repo.ts:16-34): Repo, Handle, RepoFrontend, RepoBackend, DocFrontend,
DocBackend plus document types.
"""

__version__ = "0.1.0"

from .utils.ids import (  # noqa: F401
    ActorId,
    DocId,
    DocUrl,
    HyperfileId,
    HyperfileUrl,
    RepoId,
    to_doc_url,
    to_hyperfile_url,
    url_to_id,
)

__all__ = [
    "ActorId",
    "DocId",
    "DocUrl",
    "HyperfileId",
    "HyperfileUrl",
    "RepoId",
    "to_doc_url",
    "to_hyperfile_url",
    "url_to_id",
    "__version__",
]


try:  # re-export the runtime facade once it exists (built in later milestones)
    from .repo import Repo  # noqa: F401

    __all__.append("Repo")
except ImportError:  # pragma: no cover - during early bootstrap only
    pass
