"""Handle — the user-facing subscription object.

Parity: reference src/Handle.ts:5-124 — one value subscriber, one progress
subscriber, one message subscriber per handle; change/fork/merge
conveniences; close() detaches.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Generic, List, Optional, TypeVar

T = TypeVar("T")


class Handle(Generic[T]):
    def __init__(self, doc_frontend) -> None:
        self._df = doc_frontend
        self.id = doc_frontend.doc_id
        self.url = doc_frontend.url
        self.value_fn: Optional[Callable[[T, int], None]] = None
        self.progress_fn: Optional[Callable[[dict], None]] = None
        self.message_fn: Optional[Callable[[Any], None]] = None
        self._state: Optional[T] = None
        self._index = 0
        self._have_state = threading.Event()
        self._closed = False

    # -- pushes from DocFrontend ---------------------------------------

    def push(self, state: T, index: int) -> None:
        if self._closed:
            return
        self._state = state
        self._index = index
        self._have_state.set()
        if self.value_fn is not None:
            self.value_fn(state, index)

    def push_progress(self, progress: dict) -> None:
        if not self._closed and self.progress_fn is not None:
            self.progress_fn(progress)

    def push_message(self, contents: Any) -> None:
        if not self._closed and self.message_fn is not None:
            self.message_fn(contents)

    # -- subscription api ----------------------------------------------

    def subscribe(self, fn: Callable[[T, int], None]) -> "Handle[T]":
        if self.value_fn is not None:
            raise RuntimeError("handle already has a value subscriber")
        self.value_fn = fn
        self._df.poke()  # resolve a lazy-ready (bulk-opened) doc
        if self._have_state.is_set():
            fn(self._state, self._index)
        return self

    def once(self, fn: Callable[[T, int], None]) -> "Handle[T]":
        def one(state: T, index: int) -> None:
            self.value_fn = None
            fn(state, index)

        return self.subscribe(one)

    def subscribe_progress(self, fn: Callable[[dict], None]) -> "Handle[T]":
        self.progress_fn = fn
        return self

    def subscribe_message(self, fn: Callable[[Any], None]) -> "Handle[T]":
        self.message_fn = fn
        return self

    def value(self, timeout: Optional[float] = 10.0) -> T:
        """Blocking convenience: the latest materialized state (set as soon
        as the doc is ready)."""
        self._df.poke()  # resolve a lazy-ready (bulk-opened) doc
        if not self._have_state.wait(timeout):
            raise TimeoutError(f"doc {self.id[:6]} never became ready")
        return self._state  # type: ignore[return-value]

    # -- conveniences ---------------------------------------------------

    def change(self, fn: Callable[[Any], None], message: str = "") -> None:
        self._df.change(fn, message)

    def fork(self) -> str:
        """A new doc seeded with this one's state (reference
        src/Handle.ts:21-23)."""
        return self._df._repo.fork(self.url)

    def merge(self, other: "Handle") -> "Handle[T]":
        """Adopt `other`'s actors into this doc (reference
        src/Handle.ts:33-36)."""
        self._df._repo.merge(self.url, other.url)
        return self

    def message(self, contents: Any) -> None:
        self._df.send_doc_message(contents)

    def close(self) -> None:
        self._closed = True
        self.value_fn = None
        self.progress_fn = None
        self.message_fn = None
        self._df.release_handle(self)
