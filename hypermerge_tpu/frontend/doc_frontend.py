"""DocFrontend — per-doc materialized state and change entry point.

Parity: reference src/DocFrontend.ts:23-192 — mode state machine
(pending -> read -> write), change fns queued until an actor id exists,
patches applied to the materialized state, new states fanned out to every
handle. The «blank -> preview -> final» sequence subscribers observe
matches the reference's change flow (src/DocFrontend.ts:135-150).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

from ..analysis.lockdep import make_rlock
from ..crdt.frontend_state import FrontendDoc
from ..crdt.patch import Patch
from ..utils.debug import bench, log
from ..utils.ids import to_doc_url
from .handle import Handle


class DocFrontend:
    def __init__(self, repo_frontend, doc_id: str,
                 actor_id: Optional[str] = None) -> None:
        self._repo = repo_frontend
        self.doc_id = doc_id
        self.url = to_doc_url(doc_id)
        self.actor_id = actor_id
        self.mode = "pending" if actor_id is None else "write"
        self.front = FrontendDoc()
        self.seq = 1
        self.history = 0
        self._handles: List[Handle] = []
        self._change_queue: List[tuple] = []
        self._lock = make_rlock("front.doc")
        # lazy-ready (bulk open): the backend has this doc materialized
        # but the Ready (with its snapshot patch) is fetched only when a
        # reader actually wants the value — a 10k-doc open_many must not
        # decode 10k snapshots eagerly
        self._lazy_ready = False
        self._ready_requested = False
        self._interested = False  # a reader poked before BulkReady landed
        # seq of the local change whose backend echo is outstanding.
        # Committed state only advances via echo patches, so change fns
        # must run one-per-echo: in-process the echo returns before
        # change() does (unchanged behavior); cross-process (net/ipc.py)
        # later fns queue here instead of running against stale state.
        self._inflight: Optional[int] = None

    # ------------------------------------------------------------------

    def mark_lazy_ready(self) -> None:
        """BulkReady: the backend can serve Ready on demand; fetch now
        only if a reader already wants it (a poke recorded interest, a
        subscriber attached, or a value() is blocking)."""
        with self._lock:
            self._lazy_ready = True
            want = self._interested or any(
                h.value_fn is not None for h in self._handles
            )
        if want:
            self.request_ready()

    def request_ready(self) -> None:
        with self._lock:
            if self._ready_requested or self.mode != "pending":
                return
            self._ready_requested = True
        from .. import msgs

        self._repo.to_backend.push(msgs.open_msg(self.doc_id))

    def poke(self) -> None:
        """A reader wants the value: resolve a pending lazy-ready doc.
        Interest is recorded even before BulkReady lands (backend
        messages may drain on another thread), so mark_lazy_ready can
        honor it then."""
        with self._lock:
            if self.mode != "pending":
                return
            self._interested = True
            if not self._lazy_ready:
                return
        self.request_ready()

    def handle(self) -> Handle:
        h = Handle(self)
        with self._lock:
            self._handles.append(h)
            if self.mode != "pending":
                h.push(self.front.materialize(), self.history)
        return h

    def release_handle(self, h: Handle) -> None:
        with self._lock:
            if h in self._handles:
                self._handles.remove(h)

    def change(self, fn: Callable[[Any], None], message: str = "") -> None:
        # a lazy-ready doc must materialize before the change fn runs,
        # else the fn would build ops against a blank document
        self.poke()
        with self._lock:
            needs_actor = self.mode == "pending" or self.actor_id is None
            if needs_actor:
                self._change_queue.append((fn, message))
        if needs_actor:
            # OUTSIDE self._lock: pushing to the backend queue can make
            # THIS thread the drainer of whatever is buffered there —
            # including another change's Request, which takes the
            # engine lock — while a tick holding the engine lock is
            # pushing a patch back into this doc's on_patch
            # (front.doc <-> live.engine AB/BA; caught by the first
            # HM_LOCKDEP=1 run over this tree). Queue callbacks for one
            # queue never run concurrently, so the append above is
            # already safely ordered.
            self._repo.needs_actor(self.doc_id)
            return
        self._run_change(fn, message)

    def _run_change(self, fn: Callable, message: str) -> None:
        with self._lock:
            if self._inflight is not None:
                # an echo is outstanding: the committed state this fn
                # would read is stale — run it when the echo lands
                self._change_queue.append((fn, message))
                return
            with bench("front:change"):
                request, preview = self.front.change(
                    fn, self.actor_id, self.seq, message
                )
            if request is None:
                return
            self.seq += 1
            self._inflight = request.seq
        self._fan_out(preview)  # «change preview»
        self._repo.send_request(self.doc_id, request)

    def send_doc_message(self, contents: Any) -> None:
        self._repo.send_doc_message(self.doc_id, contents)

    # ------------------------------------------------------------------
    # backend messages

    def on_ready(
        self,
        actor_id: Optional[str],
        patch_json: Optional[Dict],
        history: int,
    ) -> None:
        with self._lock:
            if self.mode != "pending":
                # Ready only initializes a pending doc (reference
                # DocFrontend.init, src/DocFrontend.ts:121-133). A doc
                # already reading/writing is AHEAD of this snapshot —
                # cross-process, the backend's Ready for a just-created
                # doc arrives after local optimistic changes, and
                # applying its blank snapshot would clobber them (the
                # backend's state reaches us through Patch echoes).
                return
            if patch_json is not None:
                with bench("front:patch"):
                    self.front.apply_patch(Patch.from_json(patch_json))
            if actor_id is not None:
                self.actor_id = actor_id
                self.seq = self.front.clock.get(actor_id, 0) + 1
            self.history = history
            self.mode = "write" if self.actor_id else "read"
            queued = list(self._change_queue)
            self._change_queue.clear()
        self._fan_out(self.front.materialize())
        for fn, message in queued:
            self._run_change(fn, message)

    def on_actor_id(self, actor_id: str) -> None:
        with self._lock:
            if self.mode == "write" and actor_id == self.actor_id:
                # duplicate notification (a NeedsActorId raced the Ready
                # that already enabled writes): resetting seq from the
                # clock here would corrupt the counter while a change's
                # echo is still in flight — the next request would reuse
                # its seq, be rejected by the backend, and strand the
                # in-flight queue forever
                return
            self.actor_id = actor_id
            if self.mode == "pending":
                # Ready (with the snapshot patch) hasn't landed: flipping
                # to write now would run queued change fns against a
                # blank doc. on_ready runs them once state exists —
                # matching the reference, where setActorId only enables
                # writes on an initialized doc (src/DocFrontend.ts:110-119).
                return
            self.seq = self.front.clock.get(actor_id, 0) + 1
            self.mode = "write"
            queued = list(self._change_queue)
            self._change_queue.clear()
        for fn, message in queued:
            self._run_change(fn, message)

    def on_patch(self, patch_json: Dict, history: int) -> None:
        queued = None
        with self._lock:
            if self.mode == "pending":
                # A patch can only precede this doc's Ready in the
                # queue when the backend announced between emitting the
                # patch and pushing the Ready — and that Ready snapshot
                # (computed under the live-engine lock, AFTER every
                # earlier emission) already contains the patch's
                # effects. Applying it to the blank doc would corrupt
                # the baseline and silently poison every later patch.
                return
            patch = Patch.from_json(patch_json)
            with bench("front:patch"):
                self.front.apply_patch(patch)
            self.history = history
            if (
                self._inflight is not None
                and patch.actor == self.actor_id
                and patch.seq == self._inflight
            ):
                self._inflight = None
                if self._change_queue:
                    queued = self._change_queue.pop(0)
            empty = patch.is_empty
        if not empty:
            self._fan_out(self.front.materialize())  # «change final» echo
        if queued is not None:
            self._run_change(*queued)
            # a no-op change fn produces no request and leaves _inflight
            # unset — keep draining, or the remaining queued changes
            # would strand until an unrelated patch happened to arrive
            while True:
                with self._lock:
                    if self._inflight is not None or not self._change_queue:
                        break
                    nxt = self._change_queue.pop(0)
                self._run_change(*nxt)

    def on_message(self, contents: Any) -> None:
        with self._lock:
            handles = list(self._handles)
        for h in handles:
            h.push_message(contents)

    def on_progress(self, progress: Dict) -> None:
        with self._lock:
            handles = list(self._handles)
        for h in handles:
            h.push_progress(progress)

    # ------------------------------------------------------------------

    def _fan_out(self, state: Any) -> None:
        with self._lock:
            handles = list(self._handles)
            history = self.history
        for h in handles:
            h.push(state, history)

    @property
    def clock(self):
        return dict(self.front.clock)
