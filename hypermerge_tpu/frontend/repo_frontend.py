"""RepoFrontend — registry of open docs; API calls -> backend messages.

Parity: reference src/RepoFrontend.ts:28-272 — create/open/doc/watch/
change/merge/fork/materialize/meta/message/close/destroy/debug, all
communicating with the backend exclusively through JSON messages.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional

from ..analysis.lockdep import make_rlock
from .. import msgs
from ..crdt import clock as clockmod
from ..crdt.change import ChangeRequest
from ..crdt.frontend_state import FrontendDoc
from ..crdt.patch import Patch
from ..utils import keys as keymod
from ..utils.debug import log
from ..utils.ids import (
    DocUrl,
    to_doc_url,
    validate_doc_url,
    validate_url,
)
from ..utils.queue import Queue
from .doc_frontend import DocFrontend
from .handle import Handle


class RepoFrontend:
    def __init__(self) -> None:
        self.to_backend: Queue = Queue("frontend:toBackend")
        self.docs: Dict[str, DocFrontend] = {}
        self._queries: Dict[int, Callable[[Any], None]] = {}
        self._next_query = 0
        self._lock = make_rlock("front.repo")
        self.files = None  # FileServerClient, attached when files start

    # ------------------------------------------------------------------
    # public api (facade delegates here)

    def create(self, init: Optional[dict] = None) -> DocUrl:
        pair = keymod.create()
        doc_id = pair.public_key
        df = DocFrontend(self, doc_id, actor_id=doc_id)
        with self._lock:
            self.docs[doc_id] = df
        self.to_backend.push(
            msgs.create_msg(pair.public_key, pair.secret_key)
        )
        if init:
            df.change(lambda d: _assign(d, init))
        return to_doc_url(doc_id)

    def open(self, url: str) -> Handle:
        doc_id = validate_doc_url(url)
        with self._lock:
            df = self.docs.get(doc_id)
            if df is None:
                df = DocFrontend(self, doc_id)
                self.docs[doc_id] = df
        self.to_backend.push(msgs.open_msg(doc_id))
        return df.handle()

    def open_many(self, urls) -> list:
        """Bulk open: one OpenBulk message, one batched backend cold
        start (device slabs), lazy Ready per doc — reading a handle (or
        subscribing/changing) fetches that doc's snapshot then. The 10k-
        doc cold start stays one XLA dispatch chain with zero eager
        per-doc decodes. Contrast the reference's per-doc open loop
        (src/RepoFrontend.ts:155-159 + src/RepoBackend.ts:238-257)."""
        doc_ids = [validate_doc_url(u) for u in urls]
        handles = []
        with self._lock:
            for doc_id in doc_ids:
                df = self.docs.get(doc_id)
                if df is None:
                    df = DocFrontend(self, doc_id)
                    self.docs[doc_id] = df
                handles.append(df.handle())
        self.to_backend.push(msgs.open_bulk_msg(doc_ids))
        return handles

    def change(self, url: str, fn: Callable[[Any], None],
               message: str = "") -> None:
        doc_id = validate_doc_url(url)
        with self._lock:
            df = self.docs.get(doc_id)
        if df is None:
            h = self.open(url)
            h.close()
            df = self.docs[doc_id]
        df.change(fn, message)

    def doc(self, url: str, cb: Optional[Callable] = None) -> Any:
        """One-shot read. With cb: async callback(doc, clock). Without:
        blocking convenience (in-process wiring resolves synchronously)."""
        h = self.open(url)
        if cb is not None:
            def once(state, index):
                cb(state, index)
                h.close()

            h.once(once)
            return None
        try:
            return h.value()
        finally:
            h.close()

    def watch(self, url: str, cb: Callable[[Any, int], None]) -> Handle:
        return self.open(url).subscribe(cb)

    def merge(
        self, url: str, target: str, timeout: Optional[float] = 30.0
    ) -> None:
        doc_id = validate_doc_url(url)
        target_id = validate_doc_url(target)
        # need the target's clock; open it (resolves synchronously
        # in-process, or when its Ready lands cross-process)
        h = self.open(target)
        done = threading.Event()

        def go(_state, _index):
            done.set()
            clock = self.docs[target_id].clock
            self.to_backend.push(
                msgs.merge_msg(doc_id, clockmod.clock_to_strs(clock))
            )
            h.close()

        h.once(go)
        if done.is_set() or timeout is None:
            return

        # Target still pending (unknown doc, gated on replication): don't
        # let the merge dangle silently forever (VERDICT r3 weak #7) —
        # surface the failure and release the handle.
        def expire():
            if not done.is_set():
                log(
                    "repo:front",
                    f"merge {doc_id[:6]} <- {target_id[:6]} timed out "
                    f"after {timeout}s: target never became ready "
                    "(unknown doc with no replicating peer?)",
                )
                h.close()

        t = threading.Timer(timeout, expire)
        t.daemon = True
        t.start()

    def fork(self, url: str) -> DocUrl:
        new_url = self.create()
        self.merge(new_url, url)
        return new_url

    def materialize(
        self, url: str, history: int, cb: Callable[[Any], None]
    ) -> None:
        """Time travel: doc state after the first `history` changes."""
        doc_id = validate_doc_url(url)

        def on_reply(payload):
            if payload is None:
                cb(None)
                return
            front = FrontendDoc()
            front.apply_patch(Patch.from_json(payload))
            cb(front.materialize())

        self._query(msgs.materialize_query(doc_id, history), on_reply)

    def read(
        self,
        url: str,
        query: Dict[str, Any],
        cb: Optional[Callable[[Any], None]] = None,
        timeout: float = 30.0,
    ) -> Any:
        """One-shot read through the backend's serving tier
        (serve/tier.py READ_KINDS: lookup/index/text/len/clock/
        history). With cb: async callback(value). Without: blocking
        convenience. Returns the read VALUE; None for an unknown /
        not-ready doc or a broken path — identical under HM_SERVE=1
        (batched device kernels over HBM-resident state) and
        HM_SERVE=0 (per-request host materialization).

        Under overload (serve/overload.py SHED state) the backend may
        answer a typed refusal instead of a value: the blocking path
        raises ``Overload`` (retry_after_s/state/tenant attached); the
        cb path delivers ``{"_overload": {...}}`` — distinguishable
        from every real read value, which is never a dict with that
        key — so an async caller can back off instead of reading the
        refusal as "doc unknown"."""
        doc_id = validate_doc_url(url)
        if cb is not None:

            def on_reply(p):
                if isinstance(p, dict) and "overload" in p:
                    cb({"_overload": p["overload"]})
                    return
                cb(None if p is None else p.get("value"))

            self._query(msgs.read_query(doc_id, query), on_reply)
            return None
        done = threading.Event()
        slot: list = [None]

        def fin(payload):
            slot[0] = payload
            done.set()

        self._query(msgs.read_query(doc_id, query), fin)
        if not done.wait(timeout):
            raise TimeoutError(f"read of {doc_id[:6]} timed out")
        payload = slot[0]
        if isinstance(payload, dict) and "overload" in payload:
            from ..serve.overload import overload_error

            raise overload_error(payload["overload"])
        return None if payload is None else payload.get("value")

    def meta(self, url: str, cb: Callable[[Any], None]) -> None:
        _scheme, id_ = validate_url(url)
        self._query(msgs.metadata_query(id_), cb)

    def telemetry(self, cb: Callable[[Any], None]) -> None:
        """The backend process' telemetry snapshot (registry counters,
        trace state) — what tools/top.py polls for live rates."""
        self._query(msgs.telemetry_query(), cb)

    def message(self, url: str, contents: Any) -> None:
        doc_id = validate_doc_url(url)
        self.to_backend.push(msgs.doc_message_msg(doc_id, contents))

    def close_doc(self, url: str) -> None:
        doc_id = validate_doc_url(url)
        with self._lock:
            self.docs.pop(doc_id, None)
        self.to_backend.push(msgs.close_msg(doc_id))

    def destroy(self, url: str) -> None:
        doc_id = validate_doc_url(url)
        with self._lock:
            self.docs.pop(doc_id, None)
        self.to_backend.push(msgs.destroy_msg(doc_id))

    def debug(self, url: str) -> Dict[str, Any]:
        doc_id = validate_doc_url(url)
        df = self.docs.get(doc_id)
        info = {
            "id": doc_id,
            "mode": df.mode if df else "closed",
            "clock": df.clock if df else {},
            "seq": df.seq if df else None,
        }
        log("repo:front", info)
        return info

    # ------------------------------------------------------------------
    # doc frontend plumbing

    def needs_actor(self, doc_id: str) -> None:
        self.to_backend.push(msgs.needs_actor_msg(doc_id))

    def send_request(self, doc_id: str, request: ChangeRequest) -> None:
        self.to_backend.push(msgs.request_msg(doc_id, request.to_json()))

    def send_doc_message(self, doc_id: str, contents: Any) -> None:
        self.to_backend.push(msgs.doc_message_msg(doc_id, contents))

    def _query(self, query: Dict, cb: Callable[[Any], None]) -> None:
        with self._lock:
            qid = self._next_query
            self._next_query += 1
            self._queries[qid] = cb
        self.to_backend.push(msgs.query_msg(qid, query))

    # ------------------------------------------------------------------
    # wiring

    def subscribe(self, subscriber: Callable[[Dict[str, Any]], None]) -> None:
        self.to_backend.subscribe(subscriber)

    def receive(self, msg: Dict[str, Any]) -> None:
        t = msg["type"]
        if t in ("Ready", "Patch", "ActorId", "DocMessageFwd", "Download"):
            df = self.docs.get(msg["id"])
            if df is None:
                return
            if t == "Ready":
                df.on_ready(msg["actorId"], msg["patch"], msg["history"])
            elif t == "Patch":
                df.on_patch(msg["patch"], msg["history"])
            elif t == "ActorId":
                df.on_actor_id(msg["actorId"])
            elif t == "DocMessageFwd":
                df.on_message(msg["contents"])
            elif t == "Download":
                df.on_progress(
                    {
                        "actor": msg["actorId"],
                        "index": msg["index"],
                        "size": msg["size"],
                        "time": msg["time"],
                    }
                )
        elif t == "Reply":
            with self._lock:
                cb = self._queries.pop(msg["queryId"], None)
            if cb is not None:
                cb(msg["payload"])
        elif t == "FileServerReady":
            from ..files.file_client import FileServerClient

            self.files = FileServerClient(msg["path"])
        elif t == "BulkReady":
            # bulk cold start: docs are ready backend-side; open
            # frontends fetch their Ready (with snapshot patch) lazily,
            # on first read — never 10k eager decodes
            for doc_id in msg["ids"]:
                df = self.docs.get(doc_id)
                if df is not None:
                    df.mark_lazy_ready()
        else:
            log("repo:front", "unknown msg", t)


def _assign(d, init: dict) -> None:
    for k, v in init.items():
        d[k] = v
