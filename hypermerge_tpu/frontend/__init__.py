"""Frontend layer: materialized docs, handles, synchronous API
(SURVEY.md §1.2)."""
