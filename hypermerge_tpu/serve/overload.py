"""The service plane: signal-driven overload control at the front door.

Every other plane defends itself against one failure mode — the serve
tier against device OOM, the WAL against crashes, the swarm against
churn — but nothing defends the PROCESS when offered load exceeds
capacity. This module is that defense: a three-state **brownout
ladder** driven by what the repo already measures, enforced at the one
place every read passes (``RepoBackend.read_doc``) and the one place
every durable write acks (the WAL group-commit gather).

States, in shed order (cheapest degradation first):

- ``HEALTHY`` — everything admitted, nothing deferred.
- ``BROWNOUT`` — cold installs shed first: reads of unresident docs
  answer from the host memo path and their device installs are
  deferred (serve/tier.py consults ``defer_install``); anti-entropy
  sweeps and gossip relay are deprioritized (net/replication.py,
  net/discovery/gossip.py). Hot resident reads are untouched.
- ``SHED`` — per-tenant token-bucket quotas enforced at the front
  door; excess reads are REFUSED with a typed Overload reply carrying
  retry-after (never an error, never a silent drop); durable writes
  are BACKPRESSURED — ``ack_extra_s`` stretches the WAL group-commit
  gather window so acks pace down — but are never dropped once acked.

Transitions use hysteresis (``HM_BROWNOUT_UP_TICKS`` consecutive
ticks over the high watermark to escalate, ``HM_BROWNOUT_DOWN_TICKS``
under the low watermark to de-escalate) so a noisy signal cannot flap
the ladder. The pressure signal is the max of three normalized feeds:
serve read p99 over its SLO, admission-queue occupancy, and WAL fsync
debt — injectable (``signals=``) so tests drive the state machine
deterministically without load.

Every decision is attributable: transitions and refusals are counters
plus trace instants tagged per tenant; ``report()`` is the
``service`` block of the Telemetry payload (tools/top.py ``[service]``
group, tools/ls.py status line, bench gating). No silent refusals.

This module is jax-free on purpose: frontend processes import the
``Overload`` exception without pulling the kernel stack (serve's
package ``__init__`` is lazy for the same reason).
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional

from .. import telemetry
from ..analysis.lockdep import make_lock

HEALTHY, BROWNOUT, SHED = 0, 1, 2
STATE_NAMES = ("healthy", "brownout", "shed")

# bound of the per-tenant table: beyond this many distinct tenants the
# least-recently-seen row is evicted (its bucket refills from scratch
# if it returns) — the controller must not grow without bound on a
# tenant-id flood
MAX_TENANTS = 256


class Overload(RuntimeError):
    """A typed refusal from the front door.

    Raised by the blocking ``Repo.read`` path when the backend answers
    with an overload payload instead of a value; carries everything a
    well-behaved client needs to back off."""

    def __init__(
        self,
        retry_after_s: float,
        state: str = "shed",
        tenant: Optional[str] = None,
    ) -> None:
        super().__init__(
            f"overloaded ({state}): retry after {retry_after_s:.3f}s"
        )
        self.retry_after_s = retry_after_s
        self.state = state
        self.tenant = tenant


def overload_error(info: Dict[str, Any]) -> Overload:
    """The ``{"overload": {...}}`` reply payload, as an exception."""
    return Overload(
        float(info.get("retry_after_s", 0.1)),
        str(info.get("state", "shed")),
        info.get("tenant"),
    )


class TokenBucket:
    """Per-tenant read quota: ``rate`` tokens/s up to ``burst``.

    Deterministic on purpose — every method takes ``now`` so tests
    drive refill with a fake clock. Not thread-safe by itself; the
    controller serializes access under ``serve.overload``."""

    __slots__ = ("rate", "burst", "tokens", "_t")

    def __init__(self, rate: float, burst: float, now: float = 0.0):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self._t = now

    def _refill(self, now: float) -> None:
        if now > self._t:
            self.tokens = min(
                self.burst, self.tokens + (now - self._t) * self.rate
            )
            self._t = now

    def take(self, now: float, n: float = 1.0) -> bool:
        self._refill(now)
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False

    def occupancy(self, now: float) -> float:
        """Fraction of burst currently SPENT (1.0 = exhausted)."""
        self._refill(now)
        return 1.0 - (self.tokens / self.burst if self.burst else 0.0)

    def retry_after_s(self, now: float, n: float = 1.0) -> float:
        """Seconds until ``n`` tokens will be available."""
        self._refill(now)
        if self.tokens >= n or self.rate <= 0:
            return 0.0
        return (n - self.tokens) / self.rate


class HistogramWindow:
    """Quantile of a telemetry Histogram's observations since the
    LAST sample — the controller's p99 feed. Windowed on purpose: a
    cumulative quantile would never step back down after one spike,
    and the de-escalation half of the hysteresis needs the signal to
    recover when the storm passes. Single-caller (the ticker)."""

    __slots__ = ("_hist", "_prev")

    def __init__(self, hist: Any) -> None:
        self._hist = hist
        self._prev: Optional[list] = None

    def quantile(self, q: float = 0.99) -> float:
        counts = self._hist.value()["buckets"]
        prev = self._prev
        self._prev = counts
        delta = (
            counts if prev is None
            else [c - p for c, p in zip(counts, prev)]
        )
        n = sum(delta)
        if n <= 0:
            return 0.0
        bounds = self._hist.buckets
        run = 0
        for i, c in enumerate(delta):
            run += c
            if run >= q * n:
                # the overflow bucket has no upper bound; report one
                # step past the last edge so the signal still moves
                return bounds[i] if i < len(bounds) else bounds[-1] * 2
        return bounds[-1] * 2


class BrownoutLadder:
    """The pure three-state machine with hysteresis; no clocks, no
    locks, no telemetry — ``observe(pressure)`` per tick returns the
    (possibly new) state. Escalates one rung after ``up_ticks``
    consecutive observations at/above ``hi``; de-escalates one rung
    after ``down_ticks`` consecutive observations at/below ``lo``;
    anything between the watermarks holds the rung and resets both
    streaks (that dead band is what prevents flapping)."""

    __slots__ = ("hi", "lo", "up_ticks", "down_ticks", "state",
                 "_up", "_down")

    def __init__(
        self,
        hi: float = 1.0,
        lo: float = 0.5,
        up_ticks: int = 3,
        down_ticks: int = 10,
    ) -> None:
        if lo >= hi:
            raise ValueError("brownout lo watermark must be < hi")
        self.hi = hi
        self.lo = lo
        self.up_ticks = max(1, int(up_ticks))
        self.down_ticks = max(1, int(down_ticks))
        self.state = HEALTHY
        self._up = 0
        self._down = 0

    def observe(self, pressure: float) -> int:
        if pressure >= self.hi:
            self._up += 1
            self._down = 0
            if self._up >= self.up_ticks and self.state < SHED:
                self.state += 1
                self._up = 0
        elif pressure <= self.lo:
            self._down += 1
            self._up = 0
            if self._down >= self.down_ticks and self.state > HEALTHY:
                self.state -= 1
                self._down = 0
        else:
            self._up = 0
            self._down = 0
        return self.state


class OverloadController:
    """One per backend: ties signals -> ladder -> enforcement.

    ``signals`` is a zero-arg callable returning a dict with any of
    ``p99_s`` (serve read p99, seconds), ``queue_frac`` (admission
    queue occupancy 0..1+), ``debt_frac`` (WAL fsync debt over its
    rotation budget, 0..1+); the backend wires the real feeds, tests
    inject synthetic ones. Pressure is the max of the normalized
    three; ``tick()`` may be called directly (deterministic tests) or
    from the background ticker (``start``)."""

    def __init__(
        self,
        signals: Optional[Callable[[], Dict[str, float]]] = None,
        now: Optional[Callable[[], float]] = None,
    ) -> None:
        self._signals = signals
        self._now = now or time.monotonic
        self._slo_s = (
            float(os.environ.get("HM_SERVICE_P99_SLO_MS", "50")) / 1e3
        )
        self._tick_s = (
            float(os.environ.get("HM_SERVICE_TICK_MS", "50")) / 1e3
        )
        self._retry_s = (
            float(os.environ.get("HM_SERVICE_RETRY_AFTER_MS", "100"))
            / 1e3
        )
        self._stretch_s = (
            float(os.environ.get("HM_SERVICE_ACK_STRETCH_MS", "25"))
            / 1e3
        )
        self._rate = float(os.environ.get("HM_QUOTA_READS_S", "512"))
        self._burst = float(os.environ.get("HM_QUOTA_BURST", "64"))
        self._ladder = BrownoutLadder(
            hi=float(os.environ.get("HM_BROWNOUT_HI", "1.0")),
            lo=float(os.environ.get("HM_BROWNOUT_LO", "0.5")),
            up_ticks=int(os.environ.get("HM_BROWNOUT_UP_TICKS", "3")),
            down_ticks=int(
                os.environ.get("HM_BROWNOUT_DOWN_TICKS", "10")
            ),
        )
        force = os.environ.get("HM_SERVICE_FORCE")
        self._force = (
            STATE_NAMES.index(force) if force in STATE_NAMES else None
        )
        self._lock = make_lock("serve.overload")
        self._state = self._force if self._force is not None else HEALTHY
        self._pressure = 0.0
        self._last: Dict[str, float] = {}
        self._tenants: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        reg = telemetry.REGISTRY
        inst = str(telemetry.next_instance())
        self._m: Dict[str, Any] = {
            k: reg.counter("service." + k, inst=inst)
            for k in (
                "transitions", "shed_reads", "brownout_reads",
                "deferred_installs", "admitted_reads",
                "deprioritized_sweeps", "deprioritized_gossip",
            )
        }
        for k in ("state", "pressure", "ack_stretch_ms"):
            self._m[k] = reg.gauge("service." + k, inst=inst)
        self._m["state"].set(self._state)

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        """Start the background ticker (idempotent; no-op when the
        state is pinned by HM_SERVICE_FORCE or no signals are wired)."""
        with self._lock:
            if (self._thread is not None or self._closed
                    or self._signals is None
                    or self._force is not None):
                return
            t = threading.Thread(
                target=self._run, name="hm-overload", daemon=True
            )
            self._thread = t
        t.start()

    def close(self) -> None:
        with self._lock:
            self._closed = True
            t = self._thread
            self._thread = None
        if t is not None:
            t.join(timeout=2.0)

    def _run(self) -> None:
        while True:
            with self._lock:
                if self._closed:
                    return
            self.tick()
            time.sleep(self._tick_s)

    # -- the ladder ----------------------------------------------------

    def tick(self, sig: Optional[Dict[str, float]] = None) -> int:
        """One controller step: read signals, fold to pressure, feed
        the ladder, publish. Returns the (possibly new) state. Tests
        may pass ``sig`` directly instead of wiring ``signals``."""
        if sig is None:
            sig = self._signals() if self._signals is not None else {}
        p99 = float(sig.get("p99_s", 0.0))
        pressure = max(
            p99 / self._slo_s if self._slo_s > 0 else 0.0,
            float(sig.get("queue_frac", 0.0)),
            float(sig.get("debt_frac", 0.0)),
        )
        with self._lock:
            self._last = dict(sig)
            self._pressure = pressure
            prev = self._state
            if self._force is not None:
                new = self._force
            else:
                new = self._ladder.observe(pressure)
            self._state = new
        self._m["pressure"].set(round(pressure, 4))
        if new != prev:
            self._m["transitions"].add(1)
            self._m["state"].set(new)
            self._m["ack_stretch_ms"].set(
                round(self._stretch_s * 1e3, 3) if new >= SHED else 0
            )
            telemetry.instant(
                "service.transition", cat="service",
                frm=STATE_NAMES[prev], to=STATE_NAMES[new],
                pressure=round(pressure, 4),
            )
        return new

    def state(self) -> int:
        # GIL-atomic snapshot (atomic_read_ok): the hot-path question
        # "are we shedding" must not take the controller lock
        return self._state

    # -- enforcement seams ---------------------------------------------

    def admit_read(
        self, tenant: Optional[str], now: Optional[float] = None
    ) -> Optional[Dict[str, Any]]:
        """The front door: None = admitted; a dict = the typed
        ``{"overload": {...}}`` reply payload (SHED state, tenant over
        quota). Counts every outcome so refusals are attributable."""
        if self._state < SHED:
            return None
        t = tenant or "local"
        if now is None:
            now = self._now()
        with self._lock:
            row = self._tenant_row(t, now)
            if row["bucket"].take(now):
                row["admitted"] += 1
                self._m["admitted_reads"].add(1)
                return None
            row["refused"] += 1
            retry = max(
                self._retry_s, row["bucket"].retry_after_s(now)
            )
        return self._refusal(t, retry)

    def refuse_overflow(
        self, tenant: Optional[str] = None, now: Optional[float] = None
    ) -> Optional[Dict[str, Any]]:
        """The admission seam for batcher-queue overflow
        (serve/tier.py): below SHED the caller degrades to the host
        path; in SHED the read is refused typed — the queue, not the
        quota, is the binding constraint, so no token is charged."""
        if self._state < SHED:
            return None
        t = tenant or "local"
        if now is None:
            now = self._now()
        with self._lock:
            row = self._tenant_row(t, now)
            row["refused"] += 1
            retry = max(
                self._retry_s, row["bucket"].retry_after_s(now)
            )
        return self._refusal(t, retry)

    def _refusal(self, tenant: str, retry: float) -> Dict[str, Any]:
        self._m["shed_reads"].add(1)
        telemetry.instant(
            "service.shed", cat="service", tenant=tenant,
            retry_after_s=round(retry, 4),
        )
        return {
            "overload": {
                "state": STATE_NAMES[SHED],
                "retry_after_s": round(retry, 4),
                "tenant": tenant,
            }
        }

    def _tenant_row(self, tenant: str, now: float) -> Dict[str, Any]:
        row = self._tenants.get(tenant)
        if row is None:
            row = {
                "bucket": TokenBucket(self._rate, self._burst, now),
                "admitted": 0,
                "refused": 0,
            }
            self._tenants[tenant] = row
            while len(self._tenants) > MAX_TENANTS:
                self._tenants.popitem(last=False)
        else:
            self._tenants.move_to_end(tenant)
        return row

    def defer_install(self, reads: int = 1) -> bool:
        """BROWNOUT+: the serve tier asks before installing a cold
        doc; True = answer its ``reads`` pending reads from the host
        memo path instead (counted as brownout reads plus the one
        deferred install)."""
        if self._state < BROWNOUT:
            return False
        self._m["brownout_reads"].add(reads)
        self._m["deferred_installs"].add(1)
        return True

    def deprioritize(self) -> bool:
        """BROWNOUT+: anti-entropy sweeps and gossip relay yield to
        foreground traffic (callers count their own skip)."""
        return self._state >= BROWNOUT

    def note_skipped_sweep(self) -> None:
        self._m["deprioritized_sweeps"].add(1)

    def note_thinned_gossip(self, n: int = 1) -> None:
        self._m["deprioritized_gossip"].add(n)

    def ack_extra_s(self) -> float:
        """SHED: extra seconds added to the WAL group-commit gather
        window — writes pace down, they are never refused."""
        return self._stretch_s if self._state >= SHED else 0.0

    # -- observability -------------------------------------------------

    def report(self) -> Dict[str, Any]:
        """The ``service`` block of the Telemetry payload."""
        now = self._now()
        with self._lock:
            tenants = {
                t: {
                    "admitted": row["admitted"],
                    "refused": row["refused"],
                    "quota_occupancy": round(
                        row["bucket"].occupancy(now), 4
                    ),
                }
                for t, row in self._tenants.items()
            }
            last = dict(self._last)
            pressure = self._pressure
            state = self._state
        return {
            "state": state,
            "state_name": STATE_NAMES[state],
            "pressure": round(pressure, 4),
            "signals": {k: round(float(v), 6) for k, v in last.items()},
            "transitions": int(self._m["transitions"].value()),
            "shed_reads": int(self._m["shed_reads"].value()),
            "brownout_reads": int(self._m["brownout_reads"].value()),
            "deferred_installs": int(
                self._m["deferred_installs"].value()
            ),
            "ack_stretch_ms": round(self.ack_extra_s() * 1e3, 3),
            "tenants": tenants,
        }
