"""The HBM residency cache: per-doc summary columns pinned on device.

A resident doc is the device half of a read: six structural lanes
(serve/kernels.py layout) stacked into ONE [LANES, N] int32 array — a
single upload per install — keyed by the serving clock the columns
were built at. The host half stays host: the value/str/float side
tables, the per-row value columns, and the element->winner-value map,
all of which only ever decode a handful of rows per read.

Install follows the PR-4 adoption idiom: the build (sidecar pack +
summary kernel + upload) runs with NO lock held; the install takes the
cache lock for dict bookkeeping only and re-checks the serving clock.
A doc whose clock moved mid-build still serves THIS batch from the
built arrays (they are correct as of read admission) but is not
cached — and a stale entry can never serve a later read, because every
read re-compares the entry clock against the doc's current serving
clock (clock-driven invalidation). Docs whose state the sidecars
cannot rebuild (_serveable_spec None — dirty/unbacked feeds) are never
installed at all: they stay on the host path rather than risk a stale
resurrection.

Eviction is a byte-bounded LRU under HM_SERVE_MAX_BYTES; device OOM
during an install sheds LRU entries and retries once before degrading
to the host path (serve/tier.py owns those counters).
"""

from __future__ import annotations

import os
from collections import OrderedDict
from typing import Any, Dict, List, Optional

import numpy as np

from ..analysis.lockdep import make_rlock
from ..crdt.change import Action
from .kernels import N_LANES, L_INSERT, L_KEY, L_LIVE, L_MAPWIN, L_OBJ, L_RANK

# below this row bucket, shape buckets would proliferate programs for
# no win; every tiny doc shares the 64-row executable
SERVE_MIN_ROWS = 64

def serve_max_bytes() -> int:
    """HM_SERVE_MAX_BYTES — read per enforcement pass so tests and
    operators can adjust the budget live."""
    return int(os.environ.get("HM_SERVE_MAX_BYTES", "268435456"))


class _Tables:
    """The batch side tables decode_value needs, without pinning the
    whole ColumnarBatch (its [D, N] column dict) in the entry."""

    __slots__ = ("strings", "floats", "bigints")

    def __init__(self, batch) -> None:
        self.strings = batch.strings
        self.floats = batch.floats
        self.bigints = batch.bigints


class ResidentDoc:
    """One doc's device lanes + host decode half, valid at `clock`."""

    __slots__ = (
        "doc_id", "clock", "n", "bucket", "dev", "action", "vkind",
        "value", "dt", "inc_total", "elem_val", "tables", "key_index",
        "nbytes", "last_use", "stale",
    )

    def __init__(
        self, doc_id: str, clock: Dict[str, int], n: int, bucket: int,
        dev: Any, host_cols: Dict[str, np.ndarray],
        elem_val: np.ndarray, tables: _Tables,
        key_index: Dict[str, int],
    ) -> None:
        self.doc_id = doc_id
        self.clock = clock
        self.n = n
        self.bucket = bucket
        self.dev = dev  # jnp [N_LANES, bucket] int32, device-resident
        self.action = host_cols["action"]
        self.vkind = host_cols["vkind"]
        self.value = host_cols["value"]
        self.dt = host_cols["dt"]
        self.inc_total = host_cols["inc_total"]
        self.elem_val = elem_val  # [n] element row -> winner value row
        self.tables = tables
        self.key_index = key_index
        self.nbytes = int(getattr(dev, "nbytes", 0)) + sum(
            int(host_cols[k].nbytes)
            for k in ("action", "vkind", "value", "dt", "inc_total")
        ) + int(elem_val.nbytes) + 512
        self.last_use = 0
        self.stale = False

    def obj_type(self, row: int) -> Optional[str]:
        """'map'/'list'/'text'/'table' for a MAKE row, 'map' for the
        root (-1), None for value rows."""
        from ..ops.materialize import _OBJ_TYPES

        if row < 0:
            return "map"
        return _OBJ_TYPES.get(int(self.action[row]))


def _to_device(stacked: np.ndarray):
    """The install's one host->device transfer — a module seam so the
    OOM tests can make the device refuse without faking a whole
    backend."""
    import jax.numpy as jnp

    return jnp.asarray(stacked)


def build_entry(backend, doc_id: str, clock: Dict[str, int]):
    """Build one doc's resident entry at `clock` — pack from the
    columnar sidecars, run the host summary kernel (or reuse the
    backend's per-doc summary memo when it already holds this clock's
    lanes), derive the host decode half, and upload the stacked device
    lanes. Runs with NO lock held. Returns (entry, memo_hit) or
    (None, False) when the sidecars cannot serve this clock.

    Raises whatever the device upload raises (the tier's OOM
    evict-and-retry wraps this call).
    """
    from ..ops.columnar import pack_docs_columns, round_up_pow2

    spec = backend._serveable_spec(clock)
    if spec is None:
        return None, False
    batch = pack_docs_columns([spec])
    c = {k: np.asarray(v[0], np.int32) for k, v in batch.cols.items()}
    n = batch.n_rows
    memo_lanes = _memo_lanes(backend, doc_id, clock, c, n)
    if memo_lanes is not None:
        live, rank, mapwin = memo_lanes
        elem_val = np.arange(n, dtype=np.int32)
        inc_total = np.zeros(n, np.int32)
    else:
        from ..ops.host_kernel import run_batch_host

        out = run_batch_host(batch)
        live = np.asarray(out.elem_live[0])
        rank = np.asarray(out.rank[0], np.int32)
        mapwin = np.asarray(out.map_winner[0])
        inc_total = np.asarray(out.inc_total[0], np.int32)
        elem_val = _elem_val_map(c, np.asarray(out.visible[0]),
                                 np.asarray(out.elem_winner[0]))
    bucket = round_up_pow2(max(n, SERVE_MIN_ROWS))
    stacked = np.zeros((N_LANES, bucket), np.int32)
    stacked[L_LIVE, :n] = live.astype(np.int32)
    stacked[L_RANK, :n] = rank
    stacked[L_OBJ, :n] = c["obj"]
    stacked[L_OBJ, n:] = -3  # pad rows match no container (root is -1)
    stacked[L_INSERT, :n] = c["insert"]
    stacked[L_KEY, :n] = c["key"]
    stacked[L_KEY, n:] = -1
    stacked[L_MAPWIN, :n] = mapwin.astype(np.int32)
    dev = _to_device(stacked)  # ONE upload per install
    host_cols = {
        "action": c["action"], "vkind": c["vkind"],
        "value": c["value"], "dt": c["dt"], "inc_total": inc_total,
    }
    entry = ResidentDoc(
        doc_id, dict(clock), n, bucket, dev, host_cols, elem_val,
        _Tables(batch), {k: i for i, k in enumerate(batch.keys)},
    )
    return entry, memo_lanes is not None


def _elem_val_map(
    c: Dict[str, np.ndarray], visible: np.ndarray, elem_winner: np.ndarray
) -> np.ndarray:
    """[n] element row -> its winning value row (the decode_patch
    elem_val rule, vectorized): a visible winning SET on the element
    overrides; otherwise the INS row's own value stands."""
    n = len(visible)
    ev = np.arange(n, dtype=np.int32)
    rows = np.nonzero(
        visible
        & (c["insert"] == 0)
        & (c["key"] < 0)
        & (c["ref"] >= 0)
        & elem_winner
    )[0]
    ev[c["ref"][rows]] = rows
    return ev


def _memo_lanes(backend, doc_id, clock, c, n):
    """Reuse the backend's per-doc summary memo (the bulk loader's host
    half) when it already holds this exact clock's summary: the install
    then skips the host kernel run entirely — the serving tier and the
    bulk path share ONE freshness rule (clock equality). Only sound
    when no row needs the lanes the memo does not carry: INC totals and
    element-override SETs fall back to the kernel run."""
    memo = getattr(backend, "_summary_memo", None)
    m = memo.get(doc_id) if memo else None
    if m is None or m["clock"] != clock or m["N"] < n:
        return None
    if np.any(c["action"] == int(Action.INC)):
        return None
    if np.any(
        (c["insert"] == 0)
        & (c["key"] < 0)
        & (c["ref"] >= 0)
        & (c["action"] == int(Action.SET))
    ):
        return None
    from ..ops.crdt_kernels import unpack_bits_le

    N = m["N"]
    mapwin = unpack_bits_le(m["mw_bits"][None], N)[0][:n]
    live = unpack_bits_le(m["el_bits"][None], N)[0][:n]
    # pseudo-rank from the memo'd element order: rank[order[i]] = N - i
    # reproduces the order under the seq_order kernel's argsort
    pos = np.empty(N, np.int64)
    pos[np.asarray(m["order"], np.int64)] = np.arange(N)
    rank = (N - pos[:n]).astype(np.int32)
    return live, rank, mapwin


class ResidencyCache:
    """doc_id -> ResidentDoc under a byte-bounded LRU. The lock guards
    table bookkeeping only — builds and uploads always run outside it
    (see module docstring)."""

    # ids remembered as "evicted" for the residency report — bounded
    # (FIFO) so a long-lived daemon cycling a huge corpus does not
    # grow the Telemetry payload with the whole doc universe
    EVICTED_REMEMBERED = 1024

    def __init__(self) -> None:
        self._lock = make_rlock("serve.cache")
        self._entries: "OrderedDict[str, ResidentDoc]" = OrderedDict()
        self._evicted: "OrderedDict[str, None]" = OrderedDict()
        self._bytes = 0
        self._use = 0

    def get_fresh(
        self, doc_id: str, clock: Dict[str, int]
    ) -> Optional[ResidentDoc]:
        """The serving invalidation check: an entry serves only when
        its build clock EQUALS the doc's current serving clock and no
        write marked it stale since."""
        with self._lock:
            e = self._entries.get(doc_id)
            if e is None or e.stale or e.clock != clock:
                return None
            self._use += 1
            e.last_use = self._use
            self._entries.move_to_end(doc_id)
            return e

    def install(self, entry: ResidentDoc) -> List[ResidentDoc]:
        """Install a built entry (replacing any older clock's entry)
        and evict LRU down to the byte budget. Returns the evicted
        entries (the tier counts them)."""
        cap = serve_max_bytes()
        with self._lock:
            evicted = []
            old = self._entries.pop(entry.doc_id, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._use += 1
            entry.last_use = self._use
            self._entries[entry.doc_id] = entry
            self._bytes += entry.nbytes
            self._evicted.pop(entry.doc_id, None)
            while self._bytes > cap and len(self._entries) > 1:
                did, lru = next(iter(self._entries.items()))
                del self._entries[did]
                self._bytes -= lru.nbytes
                self._note_evicted(did)
                evicted.append(lru)
            return evicted

    def _note_evicted(self, doc_id: str) -> None:
        """Remember (bounded) that this id was resident once.
        REQUIRES serve.cache (analysis/guards.py)."""
        self._evicted[doc_id] = None
        self._evicted.move_to_end(doc_id)
        while len(self._evicted) > self.EVICTED_REMEMBERED:
            self._evicted.popitem(last=False)

    def evict_lru(self, want_bytes: int) -> List[ResidentDoc]:
        """Shed LRU entries until `want_bytes` are freed (memory
        pressure during an install: the OOM retry path)."""
        with self._lock:
            evicted: List[ResidentDoc] = []
            freed = 0
            while self._entries and freed < want_bytes:
                did, lru = next(iter(self._entries.items()))
                del self._entries[did]
                self._bytes -= lru.nbytes
                self._note_evicted(did)
                freed += lru.nbytes
                evicted.append(lru)
            return evicted

    def mark_stale(self, doc_id: str) -> bool:
        """A write moved the doc's clock: the entry (if any) can never
        serve again (clocks never revert to the build clock), so its
        device arrays are RELEASED immediately instead of pinning the
        byte budget as dead weight until LRU pressure finds them.
        In-flight batches that already resolved the entry keep their
        reference and finish serving — those reads were admitted
        before the write's patch was delivered. True when a resident
        entry was actually invalidated."""
        with self._lock:
            e = self._entries.pop(doc_id, None)
            if e is None:
                return False
            e.stale = True
            self._bytes -= e.nbytes
            return True

    def drop(self, doc_id: str) -> None:
        with self._lock:
            e = self._entries.pop(doc_id, None)
            if e is not None:
                self._bytes -= e.nbytes
            self._evicted.pop(doc_id, None)

    @property
    def resident_bytes(self) -> int:
        # atomic_read_ok (analysis/guards.py): monitoring snapshot
        return self._bytes

    @property
    def resident_docs(self) -> int:
        with self._lock:
            return len(self._entries)

    def report(self) -> Dict[str, Any]:
        """Per-doc residency for tools/ls.py (via the Telemetry
        query): resident entries with their device bytes, plus the ids
        eviction pushed out since they were last resident."""
        with self._lock:
            return {
                "resident": {
                    did: {
                        "bytes": e.nbytes,
                        "stale": e.stale,
                        "rows": e.n,
                    }
                    for did, e in self._entries.items()
                },
                "evicted": sorted(self._evicted),
                "bytes": self._bytes,
            }

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._evicted.clear()
            self._bytes = 0
