"""The read batcher: bounded admission + debounced batch flush.

Reads enqueue from any thread (the backend receive thread, IPC
handlers, bench reader threads) and coalesce inside an
HM_SERVE_BATCH_MS window; the flush hands the whole batch to the tier,
which resolves it with one kernel dispatch per (query kind, shape
bucket). The debouncer is eager (the live-tick idiom): the leading
read of a burst flushes immediately and the flush duration itself
becomes the coalescing window, so a lone read pays ~0 latency while a
storm batches.

Admission is BOUNDED (HM_SERVE_QUEUE): a reader that would overflow
the queue is refused at submit and degrades to the host path in the
tier — backpressure becomes a counter (serve.fallbacks), never an
unbounded queue or an error.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List

from ..analysis.lockdep import make_lock
from ..utils.debounce import Debouncer


def _window_s() -> float:
    return float(os.environ.get("HM_SERVE_BATCH_MS", "1")) / 1e3


def _queue_cap() -> int:
    return int(os.environ.get("HM_SERVE_QUEUE", "4096"))


class ReadRequest:
    """One in-flight read: the query, its completion callback, and the
    resolution scratch the tier's path walk uses."""

    __slots__ = (
        "doc_id", "query", "cb", "t0", "span",
        "entry", "obj_row", "steps", "done",
    )

    def __init__(self, doc_id: str, query: Dict, cb: Callable) -> None:
        self.doc_id = doc_id
        self.query = query
        self.cb = cb
        self.t0 = 0.0
        self.span: Any = None
        self.entry: Any = None
        self.obj_row = -1
        self.steps: List = []
        self.done = False


class ReadBatcher:
    def __init__(self, flush: Callable[[List[ReadRequest]], None]) -> None:
        self._flush = flush
        self._lock = make_lock("serve.batch")
        self._depth = 0
        self._seq = 0
        self._cap = _queue_cap()  # read once: submit is the hot path
        self._closed = False
        self._deb = Debouncer(
            self._on_flush,
            window_s=_window_s(),
            name="serve-batch",
            eager=True,
        )

    def submit(self, req: ReadRequest) -> bool:
        """Enqueue for the next batch. False = queue full or batcher
        closed (the caller degrades to the host path).

        The mark happens INSIDE the lock, ordered against close():
        either this submit's mark lands before close() flips _closed
        (close's debouncer drain then flushes it), or the submit
        observes _closed and refuses — a mark can never vanish into an
        already-closed debouncer with True returned (the reader would
        block its full timeout on a callback that never fires)."""
        with self._lock:
            if self._closed or self._depth >= self._cap:
                return False
            self._depth += 1
            key = self._seq
            self._seq += 1
            self._deb.mark(key, req)
        return True

    @property
    def depth(self) -> int:
        return self._depth

    def _on_flush(self, batch: Dict[int, ReadRequest]) -> None:
        reqs = [batch[k] for k in sorted(batch)]
        with self._lock:
            self._depth -= len(reqs)
        self._flush(reqs)

    def flush_now(self, timeout: float = 5.0) -> bool:
        return self._deb.flush_now(timeout)

    def close(self, timeout: float = 5.0) -> None:
        with self._lock:
            self._closed = True
        # OUTSIDE the lock: close joins the flusher thread, and the
        # flusher's _on_flush takes the lock to settle depth
        self._deb.close(timeout)
