"""The HBM-resident read-serving tier (ISSUE 11).

Layout:
- ``resident.py`` — the residency cache: per-doc summary lanes pinned
  in device memory, keyed by serving clock, byte-bounded LRU.
- ``kernels.py`` — batched query kernels (element order, map lookup,
  counts) in the PR-7 cached program table.
- ``batcher.py`` — bounded admission + debounced batch flush.
- ``tier.py`` — ServeTier (the RepoBackend-facing surface) and
  ``host_read``, the bit-identical HM_SERVE=0 twin.
- ``overload.py`` — the service plane: brownout ladder, per-tenant
  quotas, typed Overload refusals (jax-free; frontends import it).

The tier symbols resolve lazily (PEP 562): importing
``serve.overload`` from a frontend process must not drag the kernel
stack (tier -> resident -> kernels -> jax) into a process that never
serves reads.
"""

from typing import Any

__all__ = ["READ_KINDS", "ServeTier", "host_read"]


def __getattr__(name: str) -> Any:
    if name in __all__:
        from . import tier

        return getattr(tier, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
