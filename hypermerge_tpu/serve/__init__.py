"""The HBM-resident read-serving tier (ISSUE 11).

Layout:
- ``resident.py`` — the residency cache: per-doc summary lanes pinned
  in device memory, keyed by serving clock, byte-bounded LRU.
- ``kernels.py`` — batched query kernels (element order, map lookup,
  counts) in the PR-7 cached program table.
- ``batcher.py`` — bounded admission + debounced batch flush.
- ``tier.py`` — ServeTier (the RepoBackend-facing surface) and
  ``host_read``, the bit-identical HM_SERVE=0 twin.
"""

from .tier import READ_KINDS, ServeTier, host_read

__all__ = ["READ_KINDS", "ServeTier", "host_read"]
