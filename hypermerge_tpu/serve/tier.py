"""ServeTier — reads served from HBM-resident state (ISSUE 11).

The read path of the Repo facade, rebuilt for "millions of users,
mostly readers": instead of materializing a doc host-side per request
(summary fetch + parse — the stubborn cold-open constant), the tier
keeps each warm doc's summary columns resident in device memory
(serve/resident.py) and answers reads with batched query kernels
(serve/kernels.py) over the whole concurrent read batch
(serve/batcher.py). Host work per read is a handful of scalar decodes.

Read queries (all JSON-safe; `path` is map keys (str) / sequence
indices (int) from the root):

    {"kind": "lookup", "path": [..., key]}   -> leaf value / type marker
    {"kind": "index",  "path": [...], "index": i} -> element value
    {"kind": "text",   "path": [...]}        -> joined text string
    {"kind": "len",    "path": [...]}        -> entry / element count
    {"kind": "clock"}                        -> {actor: seq}
    {"kind": "history"}                      -> history length

`host_read` is the bit-identical twin (HM_SERVE=0 and the graceful-
degradation path): per-request host materialization through
snapshot_patch -> FrontendDoc -> traversal — exactly the cost the tier
amortizes away, kept observable so the fuzz tests can pin both paths
to the same answers. Clock/history queries sit on host metadata in
both modes (the device-resident clock matrix is PR 3's mirror; no
second copy here).

Degradation ladder (never an error to the reader): unresident or
unrebuildable doc -> host path (serve.fallbacks); device OOM during
install -> evict LRU + retry once (serve.evictions_pressure) -> host
path; admission queue full -> host path. A repeated host-path read of
a clock-unmoved doc hits the tier's host memo — zero wire parse on the
warm fallback too.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional

from .. import telemetry
from ..crdt import clock as clockmod
from ..crdt.frontend_state import FrontendDoc
from ..models import Counter, Table, Text
from ..ops.columnar import decode_value
from ..utils.debug import log
from .batcher import ReadBatcher, ReadRequest
from .resident import ResidencyCache, build_entry

READ_KINDS = ("lookup", "index", "text", "len", "clock", "history")

_MAX_PATH_ROUNDS = 64  # path depth bound (per-level batched dispatches)


def _leaf(v: Any) -> Any:
    """JSON-safe leaf of a materialized value: containers collapse to
    type markers (reads address into them by path instead)."""
    if isinstance(v, Counter):
        return int(v)
    if isinstance(v, Text):
        return {"_type": "text"}
    if isinstance(v, Table):
        return {"_type": "table"}
    if isinstance(v, dict):
        return {"_type": "map"}
    if isinstance(v, list):
        return {"_type": "list"}
    return v


def _walk(tree: Any, steps: List) -> Any:
    """Follow `steps` through a materialized tree; None when the path
    breaks (missing key, index out of bounds, scalar mid-path)."""
    cur = tree
    for s in steps:
        if isinstance(s, str):
            if isinstance(cur, Table):
                cur = cur.by_id(s)
            elif isinstance(cur, dict):
                cur = cur.get(s)
            else:
                return None
        elif isinstance(s, int):
            if isinstance(cur, (list, Text)) and 0 <= s < len(cur):
                cur = cur[s]
            else:
                return None
        else:
            return None
    return cur


def host_value(doc, query: Dict) -> Any:
    """Evaluate one read against a materialized tree — the per-request
    host path (`tree` reuse is the tier's host-memo seam)."""
    return _eval_tree(_host_tree(doc), query)


def _host_tree(doc) -> Any:
    patch = doc.snapshot_patch()
    if patch is None:
        return None
    front = FrontendDoc()
    front.apply_patch(patch)
    return front.materialize()


def _eval_tree(tree: Any, query: Dict) -> Any:
    if tree is None:
        return None
    kind = query.get("kind")
    path = list(query.get("path") or [])
    if kind == "lookup":
        if not path or not isinstance(path[-1], str):
            return None
        container = _walk(tree, path[:-1])
        if isinstance(container, Table):
            return _leaf(container.by_id(path[-1]))
        if not isinstance(container, dict):
            return None
        if path[-1] not in container:
            return None
        return _leaf(container[path[-1]])
    target = _walk(tree, path)
    if kind == "text":
        return str(target) if isinstance(target, Text) else None
    if kind == "index":
        i = query.get("index")
        if not isinstance(i, int) or not isinstance(
            target, (list, Text)
        ) or not 0 <= i < len(target):
            return None
        return _leaf(target[i])
    if kind == "len":
        if isinstance(target, (dict, list, Text, Table)):
            return len(target)
        return None
    return None


def host_read(doc, query: Dict) -> Optional[Dict[str, Any]]:
    """The HM_SERVE=0 twin: one read, fully host-side, per request.
    Returns the same {"value": ...} payload the tier produces (None
    payload = doc unknown/not ready, same as the tier)."""
    kind = query.get("kind")
    if kind not in READ_KINDS:
        return None
    if kind == "clock":
        return {"value": clockmod.clock_to_strs(doc.clock)}
    if kind == "history":
        return {"value": doc.history_len}
    if not doc._announced:
        return None
    return {"value": host_value(doc, query)}


class ServeTier:
    """One per RepoBackend (HM_SERVE=1, the default)."""

    def __init__(self, backend) -> None:
        self._back = backend
        self._cache = ResidencyCache()
        self._batcher = ReadBatcher(self._flush)
        # host fallback memo: doc_id -> (clock, materialized tree,
        # byte estimate). Shares the serving invalidation check with
        # the residency cache (clock equality) under the same lock
        # class; budgeted like the device half.
        self._host_memo: "OrderedDict[str, tuple]" = OrderedDict()
        self._host_memo_bytes = 0
        self._closed = False
        reg = telemetry.REGISTRY
        inst = str(telemetry.next_instance())
        self._m: Dict[str, Any] = {
            k: reg.counter("serve." + k, inst=inst)
            for k in (
                "reads", "hits", "installs", "invalidations",
                "fallbacks", "evictions", "evictions_pressure",
                "batches", "memo_hits", "host_memo_hits", "dispatches",
                "overload_shed",
            )
        }
        for k in ("resident_docs", "resident_bytes", "queue_depth"):
            self._m[k] = reg.gauge("serve." + k, inst=inst)
        self._hist = reg.histogram("serve.read_s", inst=inst)

    # ------------------------------------------------------------------
    # public surface (RepoBackend routes reads here)

    def read_async(
        self, doc, query: Dict, cb: Callable[[Any], None]
    ) -> None:
        """Answer one read; `cb(payload)` fires on the batcher thread
        (or inline for metadata reads and degraded paths)."""
        self._m["reads"].add(1)
        kind = query.get("kind")
        req = ReadRequest(doc.id, dict(query), cb)
        req.t0 = time.perf_counter()
        req.span = telemetry.begin("serve.read", "serve", kind=kind)
        if kind == "clock":
            self._finish(req, clockmod.clock_to_strs(doc.clock))
            return
        if kind == "history":
            self._finish(req, doc.history_len)
            return
        if kind not in READ_KINDS:
            self._finish_raw(req, None)
            return
        if self._closed:
            self._m["fallbacks"].add(1)
            self._fallback(req, doc)
            return
        if not self._batcher.submit(req):
            # admission overflow is traffic pressure, not a device
            # degradation: its own signal (serve.overload_shed, never
            # serve.fallbacks), routed through the service plane — a
            # typed refusal in SHED, the host path below it
            self._m["overload_shed"].add(1)
            ctl = getattr(self._back, "overload", None)
            refusal = (
                ctl.refuse_overflow(query.get("tenant"))
                if ctl is not None else None
            )
            if refusal is not None:
                self._finish_raw(req, refusal)
            else:
                self._fallback(req, doc)
            return
        self._m["queue_depth"].set(self._batcher.depth)

    def read(self, doc, query: Dict, timeout: float = 30.0) -> Any:
        """Blocking convenience over read_async (bench, tools)."""
        done = threading.Event()
        slot: List[Any] = [None]

        def fin(payload):
            slot[0] = payload
            done.set()

        self.read_async(doc, query, fin)
        if not done.wait(timeout):
            raise TimeoutError("serve tier read timed out")
        return slot[0]

    def note_clock_moved(self, doc_id: str) -> None:
        """Write-path invalidation hook (patch emissions, live ticks):
        the doc's serving clock moved, so its resident entry and host
        memo row can never serve again. Reads would catch this at
        their own clock check anyway — the hook makes the invalidation
        eager and the counter exact. Called under the engine lock:
        bookkeeping only."""
        if self._cache.mark_stale(doc_id):
            self._m["invalidations"].add(1)
        with self._cache._lock:
            row = self._host_memo.pop(doc_id, None)
            if row is not None:
                self._host_memo_bytes -= row[2]

    def drop(self, doc_id: str) -> None:
        """close_doc/destroy: forget every cached read artifact."""
        self._cache.drop(doc_id)
        with self._cache._lock:
            row = self._host_memo.pop(doc_id, None)
            if row is not None:
                self._host_memo_bytes -= row[2]

    def residency_report(self) -> Dict[str, Any]:
        return self._cache.report()

    def flush_now(self, timeout: float = 5.0) -> bool:
        return self._batcher.flush_now(timeout)

    def close(self) -> None:
        self._closed = True
        self._batcher.close()
        self._cache.clear()
        telemetry.REGISTRY.retire(
            *self._m.values(), self._hist
        )

    # ------------------------------------------------------------------
    # the batch flush

    def _flush(self, reqs: List[ReadRequest]) -> None:
        """Resolve one admitted batch. Must never raise (a raised
        flush would re-queue the batch in the debouncer and double-
        fire callbacks): every failure lane degrades per-request."""
        try:
            with telemetry.span("serve.batch", "serve", reads=len(reqs)):
                self._m["batches"].add(1)
                self._flush_inner(reqs)
        except Exception as e:  # pragma: no cover - defensive
            log("serve", f"batch flush failed: {e!r}")
            for r in reqs:
                if not r.done:
                    self._finish_raw(r, None)
        finally:
            self._m["queue_depth"].set(self._batcher.depth)

    def _flush_inner(self, reqs: List[ReadRequest]) -> None:
        by_doc: Dict[str, List[ReadRequest]] = {}
        for r in reqs:
            by_doc.setdefault(r.doc_id, []).append(r)
        ready: List[ReadRequest] = []
        cold: List = []  # (doc, clock, reqs) needing an install
        for doc_id, rs in by_doc.items():
            doc = self._back.docs.get(doc_id)
            if doc is None or not doc._announced:
                for r in rs:
                    self._finish_raw(r, None)
                continue
            clock = doc.clock
            entry = self._cache.get_fresh(doc_id, clock)
            if entry is None:
                cold.append((doc, clock, rs))
                continue
            self._m["hits"].add(len(rs))
            self._attach(entry, rs, ready)
        # warm requests dispatch BEFORE any cold doc's install runs:
        # a hot read's latency must not absorb a cold neighbor's
        # pack+kernel (the install cost belongs to the cold reader)
        if ready:
            self._resolve(ready)
        ready = []
        ctl = getattr(self._back, "overload", None)
        for doc, clock, rs in cold:
            if ctl is not None and ctl.defer_install(len(rs)):
                # brownout: cold installs shed first — the reads
                # still answer (host memo path), the device install
                # waits for the ladder to step down
                for r in rs:
                    self._fallback(r, doc)
                continue
            entry = self._install(doc, clock)
            if entry is None:
                self._m["fallbacks"].add(len(rs))
                for r in rs:
                    self._fallback(r, doc)
                continue
            self._attach(entry, rs, ready)
        if ready:
            self._resolve(ready)

    @staticmethod
    def _attach(entry, rs, ready) -> None:
        for r in rs:
            r.entry = entry
            r.obj_row = -1
            r.steps = list(r.query.get("path") or [])
            ready.append(r)

    def _install(self, doc, clock):
        """Build + install a resident entry at `clock` (outside every
        lock), with the OOM ladder: evict LRU + retry once, then None
        (host path). A build that loses a clock race still serves this
        batch but is not cached."""
        entry = memo_hit = None
        for attempt in (0, 1):
            try:
                entry, memo_hit = build_entry(self._back, doc.id, clock)
                break
            except Exception as e:
                if (
                    attempt == 1
                    or not _looks_like_oom(e)
                    or self._cache.resident_docs == 0
                ):
                    # a deterministic build failure (corrupt sidecar,
                    # pack bug) must NOT thrash healthy residents out
                    # of the cache on every read of the one broken
                    # doc — only genuine memory pressure earns a shed
                    log("serve", f"install {doc.id[:6]} failed: {e!r}")
                    return None
                # device memory pressure: shed LRU residents and give
                # the install one more chance before degrading
                shed = self._cache.evict_lru(serve_max_bytes_retry())
                self._m["evictions_pressure"].add(len(shed))
                log(
                    "serve",
                    f"install {doc.id[:6]} hit device pressure; "
                    f"evicted {len(shed)} LRU entries, retrying",
                )
        if entry is None:
            return None  # sidecars cannot rebuild: dirty/unbacked
        self._m["installs"].add(1)
        if memo_hit:
            self._m["memo_hits"].add(1)
        if doc.clock == clock:  # install-and-recheck
            evicted = self._cache.install(entry)
            if evicted:
                self._m["evictions"].add(len(evicted))
        self._m["resident_docs"].set(self._cache.resident_docs)
        self._m["resident_bytes"].set(self._cache.resident_bytes)
        return entry

    # ------------------------------------------------------------------
    # batched path resolution + query dispatch

    def _resolve(self, reqs: List[ReadRequest]) -> None:
        from . import kernels

        live = [r for r in reqs if not r.done]
        for _round in range(_MAX_PATH_ROUNDS):
            if not live:
                return
            lookups: List[ReadRequest] = []
            orders: List[ReadRequest] = []
            fin_text: List[ReadRequest] = []
            fin_len: List[ReadRequest] = []
            fin_index: List[ReadRequest] = []
            for r in live:
                if r.steps:
                    s = r.steps[0]
                    if isinstance(s, str):
                        # a key the doc never saw resolves host-side
                        if s not in r.entry.key_index:
                            self._finish(r, None)
                        else:
                            lookups.append(r)
                    elif isinstance(s, int):
                        otype = r.entry.obj_type(r.obj_row)
                        if otype in ("list", "text"):
                            orders.append(r)
                        else:
                            self._finish(r, None)
                    else:
                        self._finish(r, None)
                    continue
                kind = r.query.get("kind")
                if kind == "text":
                    if r.entry.obj_type(r.obj_row) == "text":
                        fin_text.append(r)
                    else:
                        self._finish(r, None)
                elif kind == "index":
                    i = r.query.get("index")
                    if isinstance(i, int) and r.entry.obj_type(
                        r.obj_row
                    ) in ("list", "text"):
                        fin_index.append(r)
                    else:
                        self._finish(r, None)
                elif kind == "len":
                    fin_len.append(r)
                else:  # lookup with an exhausted path
                    self._finish(r, None)
            self._dispatch_lookups(kernels, lookups)
            self._dispatch_orders(
                kernels, orders + fin_index + fin_text
            )
            self._dispatch_counts(kernels, fin_len)
            # every round either finishes a request or consumes one of
            # its path steps, so this converges in <= depth rounds
            live = [r for r in reqs if not r.done]
        # pathological path depth: stop dispatching rounds, but keep
        # the twin contract — the host path answers what the kernel
        # walk did not finish (degrade, never a wrong None)
        for r in live:
            doc = self._back.docs.get(r.doc_id)
            if doc is None:
                self._finish_raw(r, None)
            else:
                self._m["fallbacks"].add(1)
                self._fallback(r, doc)

    def _by_bucket(self, rs: List[ReadRequest]) -> Dict[int, List]:
        groups: Dict[int, List[ReadRequest]] = {}
        for r in rs:
            groups.setdefault(r.entry.bucket, []).append(r)
        return groups

    def _dispatch_lookups(self, kernels, rs: List[ReadRequest]) -> None:
        """One map_lookup dispatch per shape bucket: resolve the next
        (string) path step of every request in the group."""
        for _bucket, group in self._by_bucket(rs).items():
            keys = [r.steps[0] for r in group]
            rows, found = kernels.map_lookup(
                [r.entry for r in group],
                [r.obj_row for r in group],
                [r.entry.key_index[k] for r, k in zip(group, keys)],
            )
            self._m["dispatches"].add(1)
            for i, r in enumerate(group):
                r.steps.pop(0)
                if not found[i]:
                    self._finish(r, None)
                    continue
                w = int(rows[i])
                if not r.steps and r.query.get("kind") == "lookup":
                    self._finish(r, self._row_leaf(r.entry, w))
                elif r.entry.obj_type(w) is not None:
                    r.obj_row = w  # descend into the linked object
                else:
                    self._finish(r, None)  # scalar mid-path

    def _dispatch_orders(self, kernels, rs: List[ReadRequest]) -> None:
        """One seq_order dispatch per bucket serves int path steps,
        final index lookups, and text joins together."""
        for _bucket, group in self._by_bucket(rs).items():
            order, count = kernels.seq_order(
                [r.entry for r in group], [r.obj_row for r in group]
            )
            self._m["dispatches"].add(1)
            for i, r in enumerate(group):
                e = r.entry
                n = int(count[i])
                if not r.steps and r.query.get("kind") == "text":
                    chars = [
                        str(self._row_value(e, int(e.elem_val[row])))
                        for row in order[i][:n]
                    ]
                    self._finish(r, "".join(chars))
                    continue
                if r.steps:  # int path step: descend through it
                    idx, descend = r.steps.pop(0), True
                else:  # final "index" query on the resolved sequence
                    idx, descend = r.query.get("index"), False
                if not isinstance(idx, int) or not 0 <= idx < n:
                    self._finish(r, None)
                    continue
                w = int(e.elem_val[int(order[i][idx])])
                if not descend:
                    self._finish(r, self._row_leaf(e, w))
                elif e.obj_type(w) is not None:
                    r.obj_row = w
                else:
                    self._finish(r, None)  # scalar mid-path

    def _dispatch_counts(self, kernels, rs: List[ReadRequest]) -> None:
        for _bucket, group in self._by_bucket(rs).items():
            n_elems, n_map = kernels.counts(
                [r.entry for r in group], [r.obj_row for r in group]
            )
            self._m["dispatches"].add(1)
            for i, r in enumerate(group):
                otype = r.entry.obj_type(r.obj_row)
                if otype in ("list", "text"):
                    self._finish(r, int(n_elems[i]))
                else:
                    self._finish(r, int(n_map[i]))

    # ------------------------------------------------------------------
    # host-side row decode (the host half of a device-served read)

    def _row_value(self, e, row: int) -> Any:
        v = decode_value(
            int(e.vkind[row]), int(e.value[row]), int(e.dt[row]),
            e.tables,
        )
        if int(e.dt[row]) == 1:  # counter: fold accumulated INCs
            v = (v or 0) + int(e.inc_total[row])
        return v

    def _row_leaf(self, e, row: int) -> Any:
        otype = e.obj_type(row)
        if otype is not None:
            return {"_type": otype}
        return self._row_value(e, row)

    # ------------------------------------------------------------------
    # degraded path + completion

    def _fallback(self, req: ReadRequest, doc) -> None:
        """Host-path read with the warm-doc memo: a clock-unmoved doc
        re-reads from its cached materialized tree — zero wire parse
        even when degraded."""
        if not doc._announced:
            self._finish_raw(req, None)
            return
        clock = doc.clock
        with self._cache._lock:
            row = self._host_memo.get(doc.id)
            tree = (
                row[1] if row is not None and row[0] == clock else None
            )
            if tree is not None:
                self._host_memo.move_to_end(doc.id)
        if tree is not None:
            self._m["host_memo_hits"].add(1)
        else:
            tree = _host_tree(doc)
            if tree is not None and doc.clock == clock:
                self._memoize_host(doc.id, clock, tree)
        self._finish(req, _eval_tree(tree, req.query))

    def _memoize_host(self, doc_id: str, clock, tree) -> None:
        from .resident import serve_max_bytes

        # byte estimate: clock rows + a flat per-change constant; the
        # cap is a budget, not an audit
        est = 256 + 96 * sum(clock.values())
        cap = serve_max_bytes()
        with self._cache._lock:
            old = self._host_memo.pop(doc_id, None)
            if old is not None:
                self._host_memo_bytes -= old[2]
            self._host_memo[doc_id] = (dict(clock), tree, est)
            self._host_memo_bytes += est
            while self._host_memo and self._host_memo_bytes > cap:
                _d, row = self._host_memo.popitem(last=False)
                self._host_memo_bytes -= row[2]

    def _finish(self, req: ReadRequest, value: Any) -> None:
        self._finish_raw(req, {"value": value})

    def _finish_raw(self, req: ReadRequest, payload: Any) -> None:
        if req.done:
            return
        req.done = True
        self._hist.observe(time.perf_counter() - req.t0)
        if req.span is not None:
            req.span.end()
        try:
            req.cb(payload)
        except Exception as e:  # a reader's cb must not kill the batch
            log("serve", f"read callback failed: {e!r}")


def serve_max_bytes_retry() -> int:
    """Bytes the OOM retry tries to free: half the budget — enough to
    matter, without flushing the whole cache for one hot doc."""
    from .resident import serve_max_bytes

    return max(1, serve_max_bytes() // 2)


def _looks_like_oom(e: Exception) -> bool:
    """Device allocation failures worth an evict-and-retry (XLA
    surfaces RESOURCE_EXHAUSTED through several exception types, so
    match on the message too)."""
    if isinstance(e, MemoryError):
        return True
    msg = str(e).lower()
    return "resource_exhausted" in msg or "out of memory" in msg
