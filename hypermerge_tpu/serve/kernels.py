"""Batched device query kernels for the read-serving tier.

One read of a resident doc never materializes anything host-side: the
structural queries — element order of a text/list object, winner row of
a (map, key) pair, live-entry counts — run as jitted programs over the
stacked summary lanes of EVERY read in the batch, so a thousand
concurrent reads cost one dispatch per (query kind, shape bucket)
instead of a thousand host summary parses.

The programs live in the PR-7 cached program table
(parallel/sharded._PROGRAMS): one trace per ("serve", kind, B, N) key
for the life of the process, pinned by the same trace_counts regression
mechanism the mesh programs use. Batch axes bucket to pow2 so a varying
read mix reuses a handful of executables.

Lane layout (serve/resident.py uploads one stacked [LANES, N] int32
array per resident doc — a single host->device transfer per install):
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

# stacked-lane row indices (ResidentDoc.dev is [LANES, N] int32)
L_LIVE = 0     # elem_live: INS rows whose element has a visible value
L_RANK = 1     # RGA order key (higher = earlier)
L_OBJ = 2      # container MAKE row (-1 = root map)
L_INSERT = 3   # 1 on element-creating ops
L_KEY = 4      # key-table index (-1 = none)
L_MAPWIN = 5   # winning visible op of its (obj, key)
N_LANES = 6

_INT32_MAX = 2**31 - 1

# qobj value that matches no container: real obj rows are >= -1 (root)
NO_OBJ = -7


def _jnp():
    import jax.numpy as jnp

    return jnp


def _program(kind: str, B: int, N: int, build):
    """A jitted serve program from the shared mesh program table —
    ("serve", kind, B, N) keys sit next to the mesh keys, and
    sharded.trace_counts pins the one-trace contract for both."""
    from ..parallel import sharded

    key = ("serve", kind, B, N)
    return sharded._program(
        key, lambda: _jit(sharded._traced(key, build()))
    )


def _jit(fn):
    import jax

    return jax.jit(fn)


def stack_entries(entries: Sequence) -> tuple:
    """The batch's resident lanes as a pow2-padded TUPLE of [LANES, N]
    device arrays. The stack into [B, LANES, N] happens INSIDE the
    jitted program (a pytree argument), so it fuses into the one
    dispatch instead of paying a per-buffer concat on the way in.
    Padding repeats the first entry's array — zero new device
    allocations; pad lanes are masked out by the NO_OBJ query pad."""
    from ..ops.columnar import round_up_pow2

    B = round_up_pow2(max(1, len(entries)))
    devs = [e.dev for e in entries]
    if len(devs) < B:
        devs.extend([devs[0]] * (B - len(devs)))
    return tuple(devs)


def _pad_q(vals: List[int], B: int, fill: int) -> np.ndarray:
    out = np.full(B, fill, np.int32)
    out[: len(vals)] = np.asarray(vals, np.int32)
    return out


def _build_map_lookup():
    def fn(arrs, qobj, qkey):
        jnp = _jnp()
        stacked = jnp.stack(arrs)
        mask = (
            (stacked[:, L_MAPWIN] != 0)
            & (stacked[:, L_KEY] == qkey[:, None])
            & (stacked[:, L_OBJ] == qobj[:, None])
        )
        row = jnp.argmax(mask, axis=1).astype(jnp.int32)
        return row, mask.any(axis=1)

    return fn


def _build_seq_order():
    def fn(arrs, qobj):
        jnp = _jnp()
        stacked = jnp.stack(arrs)
        mask = (
            (stacked[:, L_LIVE] != 0)
            & (stacked[:, L_OBJ] == qobj[:, None])
            & (stacked[:, L_INSERT] == 1)
        )
        # descending rank, ties in row order — the decode_patch element
        # order (jnp.argsort is stable)
        key = jnp.where(mask, -stacked[:, L_RANK], _INT32_MAX)
        order = jnp.argsort(key, axis=1).astype(jnp.int32)
        return order, mask.sum(axis=1).astype(jnp.int32)

    return fn


def _build_counts():
    def fn(arrs, qobj):
        stacked = _jnp().stack(arrs)
        at_obj = stacked[:, L_OBJ] == qobj[:, None]
        n_elems = (
            ((stacked[:, L_LIVE] != 0) & at_obj & (stacked[:, L_INSERT] == 1))
            .sum(axis=1)
            .astype("int32")
        )
        n_map = (
            ((stacked[:, L_MAPWIN] != 0) & at_obj).sum(axis=1).astype("int32")
        )
        return n_elems, n_map

    return fn


def map_lookup(
    entries: Sequence, qobjs: List[int], qkeys: List[int]
) -> Tuple[np.ndarray, np.ndarray]:
    """Winner value row per (doc, container, key): [B] rows + [B] found
    mask. One dispatch for the whole group."""
    jnp = _jnp()
    arrs = stack_entries(entries)
    B, N = len(arrs), arrs[0].shape[1]
    fn = _program("map_lookup", B, N, _build_map_lookup)
    row, found = fn(
        arrs,
        jnp.asarray(_pad_q(qobjs, B, NO_OBJ)),
        jnp.asarray(_pad_q(qkeys, B, -1)),
    )
    return np.asarray(row), np.asarray(found)


def seq_order(
    entries: Sequence, qobjs: List[int]
) -> Tuple[np.ndarray, np.ndarray]:
    """Element order (live INS rows, descending rank) per (doc,
    container): [B, N] row order + [B] live counts."""
    jnp = _jnp()
    arrs = stack_entries(entries)
    B, N = len(arrs), arrs[0].shape[1]
    fn = _program("seq_order", B, N, _build_seq_order)
    order, count = fn(
        arrs, jnp.asarray(_pad_q(qobjs, B, NO_OBJ))
    )
    return np.asarray(order), np.asarray(count)


def counts(
    entries: Sequence, qobjs: List[int]
) -> Tuple[np.ndarray, np.ndarray]:
    """([B] live element counts, [B] map entry counts) per container."""
    jnp = _jnp()
    arrs = stack_entries(entries)
    B, N = len(arrs), arrs[0].shape[1]
    fn = _program("counts", B, N, _build_counts)
    n_elems, n_map = fn(
        arrs, jnp.asarray(_pad_q(qobjs, B, NO_OBJ))
    )
    return np.asarray(n_elems), np.asarray(n_map)
