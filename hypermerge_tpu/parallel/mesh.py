"""Device mesh construction for doc-sharded CRDT compute.

The framework's scale axes (BASELINE.json north star: pmap/pjit doc shards
on a v5e-8):

- `dp` — document parallelism: the embarrassingly-parallel axis; every
  per-doc kernel (ops/crdt_kernels.py) shards here with zero collectives.
- `sp` — state parallelism: the actor/op axis of clock matrices and
  reduction kernels; XLA inserts the max/sum collectives over ICI when a
  reduction crosses this axis (clock unions, dominated-set queries).

The mesh maps dp to the longer physical axis so doc traffic never needs
ICI; sp collectives ride the short axis.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    n_devices: Optional[int] = None,
    sp: int = 1,
) -> Mesh:
    """A (dp, sp) mesh over the first n_devices devices."""
    devices = jax.devices()
    n = n_devices if n_devices is not None else len(devices)
    if n > len(devices):
        raise ValueError(f"asked for {n} devices, have {len(devices)}")
    if n % sp != 0:
        raise ValueError(f"n_devices {n} not divisible by sp {sp}")
    grid = np.array(devices[:n]).reshape(n // sp, sp)
    return Mesh(grid, axis_names=("dp", "sp"))


def doc_sharding(mesh: Mesh) -> NamedSharding:
    """[D, ...] arrays sharded across docs only."""
    return NamedSharding(mesh, P("dp"))


def doc_actor_sharding(mesh: Mesh) -> NamedSharding:
    """[D, A] clock matrices: docs over dp, actor axis over sp."""
    return NamedSharding(mesh, P("dp", "sp"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_to_multiple(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def device_topology() -> dict:
    """Visible-device + mesh topology summary — the audit context a
    multichip bench number needs to be interpretable on its own (the
    bench embeds it in the JSON line; `tools/meta.py --devices` prints
    it standalone). Includes whether the Pallas ICI remote-copy path is
    live (`remote_copy_capable`) — CPU host-platform meshes always run
    the lax-collective twin."""
    import jax

    from .sharded import remote_copy_capable

    devs = jax.devices()
    return {
        "n_devices": len(devs),
        "platform": devs[0].platform if devs else None,
        "device_kind": devs[0].device_kind if devs else None,
        "default_backend": jax.default_backend(),
        "mesh": {"dp": len(devs), "sp": 1},
        "ici_remote_copy": remote_copy_capable(),
        "process_count": jax.process_count(),
    }
