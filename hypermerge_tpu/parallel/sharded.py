"""Sharded batched programs: the multi-chip execution path.

The reference processes documents one at a time on one Node thread
(SURVEY.md §2.3); here the same workloads run as SPMD programs over a
(dp, sp) Mesh:

- `sharded_materialize`: the full batched CRDT replay (ops/crdt_kernels)
  with every [D, N] column sharded on dp. Per-doc compute has no cross-doc
  data flow, so XLA compiles this with zero collectives — linear scaling
  over chips.
- `sharded_clock_union` / `sharded_dominated`: GLOBAL-actor-indexed
  [D, A] clock matrices (ClockStore rows — BASELINE config 5 bulk
  queries) sharded (dp, sp); the cross-shard doc-axis reduction is an
  EXPLICIT `shard_map` collective (`lax.pmax`/`lax.pmin` over the mesh
  axes — over ICI on hardware). NOT for kernel clock outputs:
  MaterializeOut.clock is slot-LOCAL ([D, A_loc], a different actor per
  slot per doc) — decode those with `local_clock_union`.
- `step`: one full "merge step" — materialize + clock union as ONE
  `shard_map` collective program (the per-shard kernel, the per-shard
  scatter-max, and the cross-shard pmax all in one executable) — what
  the driver's multichip entry exercises end-to-end.
- `SlabRoundRobin`: the streaming-pipeline alternative to sharded
  dispatch — whole slabs round-robin (or least-loaded, HM_RR_LEAST_LOADED)
  across devices with bounded per-device in-flight queues, so chips run
  independent programs while the host packs ahead (RepoBackend bulk
  loader, HM_PIPELINE=1). Tracks per-chip dispatch busy time.
- `MeshBulkScheduler`: SlabRoundRobin's streaming married to the mesh —
  whole slabs stay pinned per chip, and the CROSS-DOC reductions over
  everything resident (clock union across every chip's slabs, the bulk
  summary gather) run as one `shard_map` collective program over the
  mesh instead of a host-side merge of per-device fetches. On real ICI
  the gather rides a Pallas `make_async_remote_copy` ring
  (`remote_copy_capable`); host-platform CPU meshes lower the same
  program through `lax` collectives, so CPU CI pins the numerics.

Every mesh program is built ONCE per (mesh, shape-bucket) key in a
module program table (`_PROGRAMS`) — repeated calls reuse the jitted
executable with zero retracing (`trace_counts` exposes per-key trace
tallies for the regression tests).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.columnar import ColumnarBatch
from ..ops.crdt_kernels import MaterializeOut, batched_kernel
from .. import telemetry
from .mesh import doc_actor_sharding, doc_sharding, pad_to_multiple

# mesh telemetry (process registry): program dispatches, retraces
# (mirrors trace_counts, which stays the per-key regression-test
# truth), and host<->device transfer bytes — the "is the mesh being
# fed" view tools/top.py renders next to pipeline queue depths.
_M_DISPATCHES = telemetry.counter("mesh.dispatches")
_M_TRACES = telemetry.counter("mesh.traces")
_M_H2D = telemetry.counter("mesh.h2d_bytes")
_M_D2H = telemetry.counter("mesh.d2h_bytes")

# narrow wire-arg order, matching ops.crdt_kernels.host_args; pad-doc
# rows must decode to action=PAD (flags=7), insert=0
_N_ARGS = 11  # flags, slot, ctr, seq, obj, key, ref, value, psrc, ptgt, da
_PAD_VALUES = (7, 0, 0, 0, -1, -1, -3, 0, -1, -1, -1)


# ---------------------------------------------------------------------------
# program table — ONE jitted program per (mesh, kind, shape bucket)
#
# The first cut of this module built a fresh `jax.jit` closure inside
# every call (`local_clock_union`, `sharded_full`'s inner `fn`), so every
# union/materialize paid a full retrace: jit caches per FUNCTION OBJECT,
# and a new closure is a new function. The table below hoists every mesh
# program behind a key; the jit object lives as long as the process and
# its own shape-cache does the rest.

_PROGRAMS: Dict[Tuple, Any] = {}
trace_counts: Dict[Tuple, int] = {}


def _program(key: Tuple, build: Callable[[], Any]) -> Any:
    fn = _PROGRAMS.get(key)
    if fn is None:
        fn = build()
        _PROGRAMS[key] = fn
    return fn


def _traced(key: Tuple, fn: Callable) -> Callable:
    """Wrap a to-be-jitted python callable so each TRACE (not each call)
    bumps trace_counts[key] — the retrace regression tests assert the
    count stays at 1 across repeated same-shape calls."""

    def wrapper(*args):
        trace_counts[key] = trace_counts.get(key, 0) + 1
        _M_TRACES.add(1)
        return fn(*args)

    return wrapper


def clear_program_cache() -> None:
    """Test hook: drop every cached mesh program and trace tally."""
    _PROGRAMS.clear()
    trace_counts.clear()


def remote_copy_capable(mesh: Optional[Mesh] = None) -> bool:
    """True when the mesh's devices can run the Pallas
    `make_async_remote_copy` ICI ring (real TPU chips with the pallas
    TPU backend importable). Host-platform CPU meshes — the CI twin —
    always lower the lax-collective variant instead. HM_ICI_PALLAS=0
    forces the lax path on hardware too (A/B and escape hatch)."""
    if os.environ.get("HM_ICI_PALLAS", "1") == "0":
        return False
    try:
        devs = (
            list(mesh.devices.flat) if mesh is not None else jax.devices()
        )
        if not devs or devs[0].platform != "tpu":
            return False
        from jax.experimental.pallas import tpu as pltpu  # noqa: F401

        return hasattr(pltpu, "make_async_remote_copy")
    except Exception:
        return False


def _pallas_ring_gather(n_devices: int, rows: int, width: int, dtype):
    """Pallas ring all-gather over the flattened mesh axis: each chip
    DMAs its [rows, width] block to its right neighbor n-1 times
    (`make_async_remote_copy`, double-buffered comm slots), assembling
    the replicated [n*rows, width] output without touching the host.
    Built only when `remote_copy_capable` — the lax.all_gather twin is
    the numerics reference on CPU CI. The ring runs over the "dp" mesh
    axis: `_gather_program` selects this path only when sp == 1, so dp
    IS the flattened device ring."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(local_ref, out_ref, comm_ref, send_sem, recv_sem):
        my_id = jax.lax.axis_index("dp")
        right = jax.lax.rem(my_id + 1, n_devices)
        out_ref[pl.ds(my_id * rows, rows), :] = local_ref[:]
        comm_ref[0] = local_ref[:]
        for step in range(n_devices - 1):
            src = (my_id - step - 1) % n_devices
            send_slot = step % 2
            recv_slot = (step + 1) % 2
            rdma = pltpu.make_async_remote_copy(
                src_ref=comm_ref.at[send_slot],
                dst_ref=comm_ref.at[recv_slot],
                send_sem=send_sem.at[send_slot],
                recv_sem=recv_sem.at[recv_slot],
                device_id=(right,),
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            rdma.start()
            rdma.wait()
            out_ref[pl.ds(src * rows, rows), :] = comm_ref[recv_slot]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((2, rows, width), dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n_devices * rows, width), dtype),
        grid_spec=grid_spec,
        compiler_params=pltpu.TPUCompilerParams(collective_id=0)
        if hasattr(pltpu, "TPUCompilerParams")
        else None,
    )


def shard_batch(batch: ColumnarBatch, mesh: Mesh):
    """Pad the doc axis to the dp size and device_put with dp sharding.

    Returns (args, A_loc, K, D_pad) — the same narrow wire args (and the
    same A_loc/K bucketing) as the single-device path, so both compile to
    the same per-shard program; only the sharding differs."""
    import time

    import numpy as np

    from ..ops import crdt_kernels as _ck
    from ..ops.crdt_kernels import (
        _enable_persistent_compile_cache,
        host_args,
    )

    _enable_persistent_compile_cache()
    dp = mesh.shape["dp"]
    D = batch.n_docs
    D_pad = pad_to_multiple(max(D, dp), dp)
    sh = doc_sharding(mesh)
    t0 = time.perf_counter()
    np_args, A, K = host_args(batch)
    t1 = time.perf_counter()

    def put(arr, pad_value):
        if D_pad != arr.shape[0]:
            pad = np.full(
                (D_pad - arr.shape[0], *arr.shape[1:]), pad_value, arr.dtype
            )
            arr = np.concatenate([arr, pad], axis=0)
        return jax.device_put(arr, sh)

    args = tuple(put(a, pv) for a, pv in zip(np_args, _PAD_VALUES))
    _ck.last_args_timings["narrow"] = t1 - t0
    _ck.last_args_timings["upload"] = time.perf_counter() - t1
    return args, A, K, D_pad


def _materialize_program(mesh: Mesh, A: int, K: int):
    key = ("materialize", mesh, A, K)

    def build():
        sh = doc_sharding(mesh)
        return jax.jit(
            _traced(key, batched_kernel(A, K)),
            in_shardings=(sh,) * _N_ARGS,
            out_shardings=MaterializeOut(
                *([sh] * len(MaterializeOut._fields))
            ),
        )

    return _program(key, build)


def _materialize_on_mesh(batch: ColumnarBatch, mesh: Mesh):
    """(out, doc_actors): the sharded batched replay plus the dp-sharded
    actor map it ran with (step reuses the map for the clock union)."""
    args, A, K, _ = shard_batch(batch, mesh)
    fn = _materialize_program(mesh, A, K)
    with mesh:
        out = fn(*args)
    return out, args[-1]


def sharded_materialize(
    batch: ColumnarBatch, mesh: Mesh
) -> MaterializeOut:
    """Batched replay sharded over dp; returns device-sharded outputs."""
    return _materialize_on_mesh(batch, mesh)[0]


def _full_program(mesh: Mesh, A: int, K: int, N: int, lean: bool):
    key = ("full", mesh, A, K, N, lean)

    def build():
        from ..ops.crdt_kernels import _summarize_wire

        sh = doc_sharding(mesh)
        kern = batched_kernel(A, K)

        def fn(*xs):
            out = kern(*xs)
            return out, _summarize_wire(out, N, A, lean)

        return jax.jit(
            _traced(key, fn),
            in_shardings=(sh,) * _N_ARGS,
            out_shardings=(
                MaterializeOut(*([sh] * len(MaterializeOut._fields))),
                sh,
            ),
        )

    return _program(key, build)


def sharded_full(batch: ColumnarBatch, mesh: Mesh, lean: bool = False):
    """(MaterializeOut, summary wire) sharded over dp — the multi-chip
    twin of ops.crdt_kernels.run_batch_full, and the dispatch the PRODUCT
    bulk loader uses when a mesh is available (RepoBackend._load_slabs):
    full lanes stay device-resident per shard for lazy patch decode, the
    fused summary buffer transfers for the materialization barrier (one
    dp-sharded [D, W] uint8 leaf). `lean` drops the wire's clock section
    — callers holding authoritative host clocks only. Per-doc compute
    has no cross-doc data flow, so XLA compiles this with zero
    collectives — linear scaling over dp."""
    args, A, K, _ = shard_batch(batch, mesh)
    jfn = _full_program(mesh, A, K, batch.n_rows, lean)
    _M_DISPATCHES.add(1)
    with mesh, telemetry.span("mesh.sharded_full", "mesh"):
        return jfn(*args)


def _pad_axes(arr, mesh: Mesh):
    """Pad [D, A] to (dp, sp) multiples with zeros (neutral for max and
    for <= domination checks)."""
    import numpy as np

    arr = np.asarray(arr)
    D, A = arr.shape
    Dp = pad_to_multiple(max(D, mesh.shape["dp"]), mesh.shape["dp"])
    Ap = pad_to_multiple(max(A, mesh.shape["sp"]), mesh.shape["sp"])
    if (Dp, Ap) != (D, A):
        out = np.zeros((Dp, Ap), arr.dtype)
        out[:D, :A] = arr
        arr = out
    return arr, D, A


def _union_program(mesh: Mesh):
    """[D, A] (dp, sp)-sharded -> [A] sp-sharded union: per-shard doc
    max, then an explicit pmax collective across the dp axis."""
    key = ("union", mesh)

    def build():
        def f(c):
            return jax.lax.pmax(jnp.max(c, axis=0), "dp")

        return jax.jit(
            shard_map(
                _traced(key, f),
                mesh=mesh,
                in_specs=P("dp", "sp"),
                out_specs=P("sp"),
                check_rep=False,
            )
        )

    return _program(key, build)


def sharded_clock_union(clocks, mesh: Mesh):
    """[D, A] -> [A] union across a (dp, sp)-sharded clock matrix whose
    columns are GLOBAL actor indices (ClockStore rows); the dp-axis
    max-reduce is an explicit shard_map `lax.pmax` — an ICI collective
    on hardware. Kernel clock outputs are slot-local — use
    `local_clock_union` for those."""
    arr, _D, A = _pad_axes(clocks, mesh)
    arr = jax.device_put(arr, doc_actor_sharding(mesh))
    fn = _union_program(mesh)
    with mesh:
        return fn(arr)[:A]


def _dominated_program(mesh: Mesh):
    """[D, A], [A] -> [D] bool: per-shard <= check, then an explicit
    pmin collective ANDs the verdicts across the sp axis."""
    key = ("dominated", mesh)

    def build():
        def f(c, q):
            part = jnp.all(c <= q[None, :], axis=-1)
            return jax.lax.pmin(part.astype(jnp.int32), "sp") > 0

        return jax.jit(
            shard_map(
                _traced(key, f),
                mesh=mesh,
                in_specs=(P("dp", "sp"), P("sp")),
                out_specs=P("dp"),
                check_rep=False,
            )
        )

    return _program(key, build)


def sharded_dominated(clocks, query, mesh: Mesh):
    """[D, A], [A] -> [D] bool: which docs' clocks the query dominates.
    The actor-axis `all` reduction crosses sp shards (shard_map pmin)."""
    import numpy as np

    arr, D, A = _pad_axes(clocks, mesh)
    q = np.zeros((arr.shape[1],), arr.dtype)
    q[:A] = np.asarray(query)
    arr = jax.device_put(arr, doc_actor_sharding(mesh))
    q = jax.device_put(q, NamedSharding(mesh, P("sp")))
    fn = _dominated_program(mesh)
    with mesh:
        return fn(arr, q)[:D]


def _scatter_union(clock, doc_actors, n_actors: int):
    """Per-shard scatter-max of slot-local clocks into global actor
    rows: [d, A_loc] x [d, A_loc] -> [n_actors]."""
    return (
        jnp.zeros(n_actors + 1, jnp.int32)
        .at[jnp.where(doc_actors >= 0, doc_actors, n_actors).ravel()]
        .max(jnp.where(doc_actors >= 0, clock, 0).ravel())[:n_actors]
    )


def _local_union_program(mesh: Mesh, n_actors: int):
    key = ("local_union", mesh, n_actors)

    def build():
        def f(c, da):
            u = _scatter_union(c, da, n_actors)
            return jax.lax.pmax(jax.lax.pmax(u, "dp"), "sp")

        return jax.jit(
            shard_map(
                _traced(key, f),
                mesh=mesh,
                in_specs=(P("dp"), P("dp")),
                out_specs=P(),
                check_rep=False,
            )
        )

    return _program(key, build)


def local_clock_union(clock, doc_actors, n_actors: int, mesh: Mesh):
    """[D, A_loc] local-slot clocks + [D, A_loc] actor maps -> [n_actors]
    global union. Each shard scatter-maxes its docs, then one explicit
    pmax collective (shard_map) replicates the union over the mesh —
    max-allreduce over ICI on hardware. The program is cached per
    (mesh, n_actors): repeated calls never retrace."""
    fn = _local_union_program(mesh, n_actors)
    with mesh:
        return fn(clock, doc_actors)


def _step_program(mesh: Mesh, A: int, K: int, n_actors: int):
    """ONE collective program for the full merge step: the per-shard
    kernel, the per-shard scatter-max clock union, and the cross-shard
    pmax — materialize + union in a single executable over the mesh."""
    key = ("step", mesh, A, K, n_actors)

    def build():
        kern = batched_kernel(A, K)

        def f(*args):
            out = kern(*args)
            u = _scatter_union(out.clock, args[-1], n_actors)
            u = jax.lax.pmax(jax.lax.pmax(u, "dp"), "sp")
            return out, u

        return jax.jit(
            shard_map(
                _traced(key, f),
                mesh=mesh,
                in_specs=(P("dp"),) * _N_ARGS,
                out_specs=(
                    MaterializeOut(
                        *([P("dp")] * len(MaterializeOut._fields))
                    ),
                    P(),
                ),
                check_rep=False,
            )
        )

    return _program(key, build)


def step(batch: ColumnarBatch, mesh: Mesh):
    """One full merge step: materialize everything + union every clock,
    as ONE shard_map collective program over the mesh. This is the
    framework's 'training step' analogue — the complete device-side
    work of a bulk sync cycle."""
    args, A, K, _ = shard_batch(batch, mesh)
    n_actors = max(1, len(batch.actors))
    fn = _step_program(mesh, A, K, n_actors)
    _M_DISPATCHES.add(1)
    with mesh, telemetry.span("mesh.step", "mesh"):
        return fn(*args)


def _gather_program(mesh: Mesh, dtype, force_lax: bool = False):
    """[rows, W] sharded over the flattened mesh axis -> replicated
    [rows, W]: the bulk summary gather as one collective program. On
    meshes whose chips pass `remote_copy_capable` the inner gather is a
    Pallas `make_async_remote_copy` ring (sp == 1 ring topology);
    everywhere else (CPU CI, sp > 1) it is `lax.all_gather` — identical
    numerics, different transport. A Pallas failure can surface at
    TRACE time (caught inside, falls back per-build) or at COMPILE
    time (outside any try here — the caller retries with
    `force_lax=True`, which keys a separate cached program)."""
    n = mesh.devices.size
    use_pallas = (
        not force_lax
        and remote_copy_capable(mesh)
        and mesh.shape["sp"] == 1
    )
    key = ("gather", mesh, jnp.dtype(dtype).name, use_pallas)

    def build():
        def lax_gather(x):
            g = jax.lax.all_gather(x, "sp", axis=0, tiled=True)
            return jax.lax.all_gather(g, "dp", axis=0, tiled=True)

        def pallas_gather(x):
            try:
                ring = _pallas_ring_gather(
                    n, x.shape[0], x.shape[1], x.dtype
                )
                return ring(x)
            except Exception:
                # pallas TRACE failed for this shape/backend: the lax
                # twin is always correct (compile-time failures are
                # the caller's force_lax retry)
                return lax_gather(x)

        f = pallas_gather if use_pallas else lax_gather
        return jax.jit(
            shard_map(
                _traced(key, f),
                mesh=mesh,
                in_specs=P(("dp", "sp")),
                out_specs=P(),
                check_rep=False,
            )
        )

    return _program(key, build)


def _combine_partials_program(mesh: Mesh):
    """[n_chips, A] (one row per chip, sharded over the flattened mesh
    axis) -> replicated [A] max: the cross-chip clock-union combine."""
    key = ("combine", mesh)

    def build():
        def f(x):
            u = jnp.max(x, axis=0)
            return jax.lax.pmax(jax.lax.pmax(u, "dp"), "sp")

        return jax.jit(
            shard_map(
                _traced(key, f),
                mesh=mesh,
                in_specs=P(("dp", "sp")),
                out_specs=P(),
                check_rep=False,
            )
        )

    return _program(key, build)


class SlabRoundRobin:
    """Stream WHOLE slabs across visible devices with bounded
    per-device in-flight queues — the streaming pipeline's multi-chip
    dispatch (RepoBackend._dispatch_slab under HM_PIPELINE=1).

    Where `sharded_full` splits one slab across the mesh (dp sharding:
    one program, every chip in lockstep, the host blocked feeding all
    chips at once), round-robin keeps each slab whole on one chip and
    streams successive slabs to successive chips. Chips run independent
    programs, so while chip k computes slab N the host packs slab N+1
    for chip k+1 — the 8-chip projection becomes an actual overlapped
    run instead of an 8x divide of a serial device stage. Same kernels
    (materialize_full_device / the lean twin), same (A_loc, K) buckets,
    so results are bit-identical to the single-device and sharded
    paths.

    Placement: strict round-robin by default; HM_RR_LEAST_LOADED=1 (or
    least_loaded=True) picks the device with the SHORTEST in-flight
    queue instead — a chip wedged on a slow slab is skipped while idle
    chips take new work — with the round-robin cursor as the FIFO
    tiebreak so equal loads still cycle.

    Backpressure: at most `depth` (HM_RR_DEPTH, default 2) unfetched
    slabs per device; dispatching onto a saturated device blocks on its
    OLDEST outstanding summary, which bounds host staging and device
    memory to depth x n_devices slabs.

    Accounting: `t_dispatch_chip[i]` accumulates per-chip dispatch busy
    seconds and `slabs_per_chip[i]` the slab count; `last_device` is the
    index the most recent dispatch landed on (the bulk loader's per-chip
    stats and the fetch stage's chip attribution read these)."""

    def __init__(
        self, devices=None, depth: int = None, least_loaded: bool = None
    ) -> None:
        self.devices = list(
            devices if devices is not None else jax.devices()
        )
        self.depth = (
            depth
            if depth is not None
            else max(1, int(os.environ.get("HM_RR_DEPTH", "2")))
        )
        self.least_loaded = (
            least_loaded
            if least_loaded is not None
            else os.environ.get("HM_RR_LEAST_LOADED", "0") == "1"
        )
        self._next = 0
        self._inflight = {i: [] for i in range(len(self.devices))}
        self.t_dispatch_chip = [0.0] * len(self.devices)
        self.slabs_per_chip = [0] * len(self.devices)
        self.last_device: Optional[int] = None

    def device_index(self, device) -> Optional[int]:
        """Index of a jax device within this scheduler (None when it is
        not one of ours) — the fetch stage attributes per-chip busy time
        by the wire buffer's device."""
        try:
            return self.devices.index(device)
        except ValueError:
            return None

    def cursor(self) -> int:
        """Round-robin cursor snapshot. The bulk loader reads it on the
        caller thread BEFORE the pipeline starts; combined with
        pack_device_for it lets pack workers predict placement ahead of
        dispatch."""
        return self._next

    def pack_device_for(self, seq: int, cursor0: int):
        """Device slab `seq` of a load will be dispatched to, given the
        cursor snapshot `cursor0` taken when the load started. Valid
        because strict round-robin consumes slabs in seq order straight
        off the cursor — the device-pack path (HM_DEVICE_PACK=1) uses
        it to build the packed columns ON the chip that will run the
        materialize kernel, so no cross-chip copy rides the dispatch.
        Least-loaded placement is load-dependent, so no prediction is
        possible: returns None (pack uses the default device)."""
        if self.least_loaded:
            return None
        return self.devices[(cursor0 + seq) % len(self.devices)]

    def _pick_device(self) -> int:
        """Next device index. Round-robin: the cursor, regardless of
        load (the dispatch below blocks if it is saturated). Least
        loaded: the shortest in-flight queue, scanning from the cursor
        so ties break FIFO — a saturated device is SKIPPED while any
        other has room."""
        n = len(self.devices)
        if not self.least_loaded:
            i = self._next
            self._next = (self._next + 1) % n
            return i
        best = None
        best_len = None
        for k in range(n):
            i = (self._next + k) % n
            qlen = len(self._inflight[i])
            if best_len is None or qlen < best_len:
                best, best_len = i, qlen
                if qlen == 0:
                    break
        self._next = (best + 1) % n
        return best

    def dispatch(self, batch: ColumnarBatch, lean: bool = False):
        """(MaterializeOut, summary wire) on the chosen device; blocks
        only when that device already holds `depth` unfetched slabs.
        The kernel entry is run_batch_full with a pinned device — the
        same code path as the single-device twin, so the two cannot
        diverge."""
        import time

        from ..ops.crdt_kernels import run_batch_full

        i = self._pick_device()
        q = self._inflight[i]
        while len(q) >= self.depth:
            q.pop(0).block_until_ready()
        t0 = time.perf_counter()
        with telemetry.span("mesh.dispatch", "mesh"):
            out, summary = run_batch_full(
                batch, lean=lean, device=self.devices[i]
            )
        _M_DISPATCHES.add(1)
        _M_H2D.add(
            sum(a.nbytes for a in batch.cols.values())
            + batch.psrc.nbytes
            + batch.ptgt.nbytes
        )
        self.t_dispatch_chip[i] += time.perf_counter() - t0
        self.slabs_per_chip[i] += 1
        self.last_device = i
        q.append(summary)
        return out, summary

    def drain(self) -> None:
        """Block until every outstanding dispatch has completed."""
        for q in self._inflight.values():
            while q:
                q.pop(0).block_until_ready()

    def release(self) -> None:
        """Drop the backpressure refs without blocking — called when a
        bulk load finishes dispatching. The consumers (pending summary
        entries / the fetch worker) hold their own refs; keeping these
        would pin depth x n_devices device buffers for the lifetime of
        the cached scheduler."""
        for q in self._inflight.values():
            q.clear()


class MeshBulkScheduler(SlabRoundRobin):
    """SlabRoundRobin's streaming dispatch + shard_map collective
    cross-doc reductions: the mesh-native bulk sync scheduler.

    Dispatch is UNCHANGED from the round-robin parent (whole slabs
    pinned per chip, host packs slab N+1 while chip k computes slab N,
    identical kernels so summaries stay bit-identical) — but every
    dispatched slab's device-resident outputs are also tracked per
    chip, so the cross-doc reductions that used to be a host-side merge
    of per-device fetches become collective programs over the mesh:

    - `collective_clock_union(n_actors)`: each chip pre-reduces ITS
      resident slabs' slot-local clocks (one tiny scatter-max program
      per slab, executed where the data lives — no transfer), the
      per-chip partials assemble zero-copy into one mesh-sharded
      [n_chips, n_actors] array, and ONE shard_map pmax program
      replicates the global union — a single [n_actors] fetch instead
      of n_chips fetch-and-merge round trips.
    - `gather_summaries()`: every chip's resident summary wires stack
      on-chip, assemble into one mesh-sharded [rows, W] array, and ONE
      collective gather program (`lax.all_gather`, or the Pallas
      `make_async_remote_copy` ring on capable ICI) replicates them —
      the host reads the whole load's summaries in ONE transfer, in
      dispatch order.

    Tracking is OPT-IN (`track_resident`): callers that will run the
    collective reductions (the bulk-sync merge layer, the measured
    bench, tests) pay the per-dispatch actor-map upload and keep
    wire/clock refs pinned until `reset_resident()`; the PRODUCT bulk
    loader constructs with tracking OFF — its barrier fetches per slab
    on the overlapped fetch workers, so tracking there would pin every
    slab's device wire for no consumer. Track + reduce state resets
    with `reset_resident()` (a new bulk load) — the backpressure/
    release contract is the parent's."""

    def __init__(
        self,
        mesh: Mesh,
        depth: int = None,
        least_loaded: bool = None,
        track_resident: bool = True,
    ) -> None:
        super().__init__(
            list(mesh.devices.flat), depth, least_loaded=least_loaded
        )
        self.mesh = mesh
        self.track_resident = track_resident
        # per chip: (clock ref [D, A_loc], doc_actors ref [D, A_loc])
        self._resident_clocks: Dict[int, List] = {
            i: [] for i in range(len(self.devices))
        }
        # per chip: (dispatch sequence number, n_docs, wire ref [D, W])
        self._resident_wires: Dict[int, List] = {
            i: [] for i in range(len(self.devices))
        }
        self._seq = 0

    def reset_resident(self) -> None:
        """Forget tracked device refs (start of a new bulk load)."""
        for d in (self._resident_clocks, self._resident_wires):
            for q in d.values():
                q.clear()
        self._seq = 0

    def dispatch(self, batch: ColumnarBatch, lean: bool = False):
        from ..ops.crdt_kernels import bucket_doc_actors

        out, summary = super().dispatch(batch, lean=lean)
        if not self.track_resident:
            return out, summary
        i = self.last_device
        da, _A, _K = bucket_doc_actors(batch)
        da_ref = jax.device_put(da, self.devices[i])
        self._resident_clocks[i].append((out.clock, da_ref))
        self._resident_wires[i].append(
            (self._seq, batch.n_docs, summary)
        )
        self._seq += 1
        return out, summary

    # -- collective reductions over everything resident -----------------

    def _chip_partial(self, items, n_actors: int, device):
        """Max-fold one chip's resident (clock, da) refs into a [1,
        n_actors] partial ON that chip. Data is committed to the chip,
        so the cached scatter program executes there — no host hop."""
        key = ("chip_union", n_actors)

        def build():
            def f(c, da, acc):
                return jnp.maximum(acc, _scatter_union(c, da, n_actors))

            return jax.jit(_traced(key, f))

        fn = _program(key, build)
        acc = jax.device_put(
            jnp.zeros((n_actors,), jnp.int32), device
        )
        for clock, da in items:
            acc = fn(clock, da, acc)
        return acc.reshape(1, n_actors)

    def collective_clock_union(self, n_actors: int):
        """[n_actors] global union of every resident slab's clocks:
        per-chip pre-reduce, then ONE shard_map pmax collective across
        the mesh. Replaces fetching each chip's partial and merging on
        host."""
        import numpy as np

        n_actors = max(1, n_actors)
        partials = [
            self._chip_partial(
                self._resident_clocks[i], n_actors, self.devices[i]
            )
            for i in range(len(self.devices))
        ]
        sh = NamedSharding(self.mesh, P(("dp", "sp")))
        arr = jax.make_array_from_single_device_arrays(
            (len(self.devices), n_actors), sh, partials
        )
        fn = _combine_partials_program(self.mesh)
        with self.mesh:
            return np.asarray(fn(arr))

    def gather_summaries(self):
        """Every resident summary wire, host-side, in DISPATCH order:
        [(seq, n_docs, np wire rows)] via ONE collective gather program
        per wire width. Chips stack their wires locally (device-pinned
        concat + zero-pad to the max per-chip row count), the stacks
        assemble into one mesh-sharded array, and the gather collective
        replicates it — a single device->host transfer serves the whole
        load, replacing one fetch per slab per chip."""
        import numpy as np

        # group by wire width: one collective per distinct [.., W]
        by_w: Dict[int, Dict[int, List]] = {}
        for i, items in self._resident_wires.items():
            for seq, n_docs, wire in items:
                by_w.setdefault(wire.shape[1], {}).setdefault(
                    i, []
                ).append((seq, n_docs, wire))
        out = []
        for W, per_chip in sorted(by_w.items()):
            rows_per_chip = [
                sum(int(w.shape[0]) for _s, _n, w in per_chip.get(i, []))
                for i in range(len(self.devices))
            ]
            rows = max(max(rows_per_chip), 1)
            stacks = []
            for i in range(len(self.devices)):
                items = per_chip.get(i, [])
                key = ("wire_stack", W, rows, len(items))

                def build(items=items, rows=rows, W=W):
                    def f(*wires):
                        parts = list(wires) + [
                            jnp.zeros(
                                (
                                    rows
                                    - sum(
                                        w.shape[0] for w in wires
                                    ),
                                    W,
                                ),
                                jnp.uint8,
                            )
                        ]
                        return jnp.concatenate(parts, axis=0)

                    return jax.jit(_traced(key, f))

                fn = _program(key, build)
                if items:
                    stacks.append(fn(*[w for _s, _n, w in items]))
                else:
                    stacks.append(
                        jax.device_put(
                            jnp.zeros((rows, W), jnp.uint8),
                            self.devices[i],
                        )
                    )
            sh = NamedSharding(self.mesh, P(("dp", "sp")))
            arr = jax.make_array_from_single_device_arrays(
                (len(self.devices) * rows, W), sh, stacks
            )
            gfn = _gather_program(self.mesh, jnp.uint8)
            try:
                with self.mesh:
                    host = np.asarray(gfn(arr))
            except Exception:
                # a Pallas ring that traced but failed to COMPILE (or
                # execute) for this shape: retry on the lax-collective
                # twin, which is always correct. Never retry a lax
                # failure — that is a real error.
                if not (
                    remote_copy_capable(self.mesh)
                    and self.mesh.shape["sp"] == 1
                ):
                    raise
                gfn = _gather_program(
                    self.mesh, jnp.uint8, force_lax=True
                )
                with self.mesh:
                    host = np.asarray(gfn(arr))
            _M_D2H.add(host.nbytes)
            for i in range(len(self.devices)):
                base = i * rows
                for seq, n_docs, wire in per_chip.get(i, []):
                    n = int(wire.shape[0])
                    out.append((seq, n_docs, host[base : base + n]))
                    base += n
        out.sort(key=lambda t: t[0])
        return out
