"""Sharded batched programs: the multi-chip execution path.

The reference processes documents one at a time on one Node thread
(SURVEY.md §2.3); here the same workloads run as SPMD programs over a
(dp, sp) Mesh:

- `sharded_materialize`: the full batched CRDT replay (ops/crdt_kernels)
  with every [D, N] column sharded on dp. Per-doc compute has no cross-doc
  data flow, so XLA compiles this with zero collectives — linear scaling
  over chips.
- `sharded_clock_union` / `sharded_dominated`: GLOBAL-actor-indexed
  [D, A] clock matrices (ClockStore rows — BASELINE config 5 bulk
  queries) sharded (dp, sp); the doc-axis reduction crosses shards, so
  XLA inserts max-reduce collectives over ICI. NOT for kernel clock
  outputs: MaterializeOut.clock is slot-LOCAL ([D, A_loc], a different
  actor per slot per doc) — decode those with `local_clock_union`.
- `step`: one full "merge step" combining materialize + local clock
  union — what dryrun_multichip exercises end-to-end.
- `SlabRoundRobin`: the streaming-pipeline alternative to sharded
  dispatch — whole slabs round-robin across devices with bounded
  per-device in-flight queues, so chips run independent programs while
  the host packs ahead (RepoBackend bulk loader, HM_PIPELINE=1).
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.columnar import ColumnarBatch
from ..ops.crdt_kernels import MaterializeOut, batched_kernel
from .mesh import doc_actor_sharding, doc_sharding, pad_to_multiple

# narrow wire-arg order, matching ops.crdt_kernels.host_args; pad-doc
# rows must decode to action=PAD (flags=7), insert=0
_N_ARGS = 11  # flags, slot, ctr, seq, obj, key, ref, value, psrc, ptgt, da
_PAD_VALUES = (7, 0, 0, 0, -1, -1, -3, 0, -1, -1, -1)


def shard_batch(batch: ColumnarBatch, mesh: Mesh):
    """Pad the doc axis to the dp size and device_put with dp sharding.

    Returns (args, A_loc, K, D_pad) — the same narrow wire args (and the
    same A_loc/K bucketing) as the single-device path, so both compile to
    the same per-shard program; only the sharding differs."""
    import time

    import numpy as np

    from ..ops import crdt_kernels as _ck
    from ..ops.crdt_kernels import (
        _enable_persistent_compile_cache,
        host_args,
    )

    _enable_persistent_compile_cache()
    dp = mesh.shape["dp"]
    D = batch.n_docs
    D_pad = pad_to_multiple(max(D, dp), dp)
    sh = doc_sharding(mesh)
    t0 = time.perf_counter()
    np_args, A, K = host_args(batch)
    t1 = time.perf_counter()

    def put(arr, pad_value):
        if D_pad != arr.shape[0]:
            pad = np.full(
                (D_pad - arr.shape[0], *arr.shape[1:]), pad_value, arr.dtype
            )
            arr = np.concatenate([arr, pad], axis=0)
        return jax.device_put(arr, sh)

    args = tuple(put(a, pv) for a, pv in zip(np_args, _PAD_VALUES))
    _ck.last_args_timings["narrow"] = t1 - t0
    _ck.last_args_timings["upload"] = time.perf_counter() - t1
    return args, A, K, D_pad


def _materialize_on_mesh(batch: ColumnarBatch, mesh: Mesh):
    """(out, doc_actors): the sharded batched replay plus the dp-sharded
    actor map it ran with (step reuses the map for the clock union)."""
    args, A, K, _ = shard_batch(batch, mesh)
    fn = jax.jit(
        batched_kernel(A, K),
        in_shardings=(doc_sharding(mesh),) * _N_ARGS,
        out_shardings=MaterializeOut(
            *([doc_sharding(mesh)] * len(MaterializeOut._fields))
        ),
    )
    with mesh:
        out = fn(*args)
    return out, args[-1]


def sharded_materialize(
    batch: ColumnarBatch, mesh: Mesh
) -> MaterializeOut:
    """Batched replay sharded over dp; returns device-sharded outputs."""
    return _materialize_on_mesh(batch, mesh)[0]


def sharded_full(batch: ColumnarBatch, mesh: Mesh, lean: bool = False):
    """(MaterializeOut, summary wire) sharded over dp — the multi-chip
    twin of ops.crdt_kernels.run_batch_full, and the dispatch the PRODUCT
    bulk loader uses when a mesh is available (RepoBackend._load_slabs):
    full lanes stay device-resident per shard for lazy patch decode, the
    fused summary buffer transfers for the materialization barrier (one
    dp-sharded [D, W] uint8 leaf). `lean` drops the wire's clock section
    — callers holding authoritative host clocks only. Per-doc compute
    has no cross-doc data flow, so XLA compiles this with zero
    collectives — linear scaling over dp."""
    from ..ops.crdt_kernels import _summarize_wire, batched_kernel

    args, A, K, _ = shard_batch(batch, mesh)
    sh = doc_sharding(mesh)

    def fn(*xs):
        out = batched_kernel(A, K)(*xs)
        return out, _summarize_wire(out, batch.n_rows, A, lean)

    jfn = jax.jit(
        fn,
        in_shardings=(sh,) * _N_ARGS,
        out_shardings=(
            MaterializeOut(*([sh] * len(MaterializeOut._fields))),
            sh,
        ),
    )
    with mesh:
        return jfn(*args)


@partial(jax.jit, static_argnames=())
def _union_reduce(clocks):
    return jnp.max(clocks, axis=0)


def _pad_axes(arr, mesh: Mesh):
    """Pad [D, A] to (dp, sp) multiples with zeros (neutral for max and
    for <= domination checks)."""
    import numpy as np

    arr = np.asarray(arr)
    D, A = arr.shape
    Dp = pad_to_multiple(max(D, mesh.shape["dp"]), mesh.shape["dp"])
    Ap = pad_to_multiple(max(A, mesh.shape["sp"]), mesh.shape["sp"])
    if (Dp, Ap) != (D, A):
        out = np.zeros((Dp, Ap), arr.dtype)
        out[:D, :A] = arr
        arr = out
    return arr, D, A


def sharded_clock_union(clocks, mesh: Mesh):
    """[D, A] -> [A] union across a (dp, sp)-sharded clock matrix whose
    columns are GLOBAL actor indices (ClockStore rows); the dp-axis
    max-reduce becomes an ICI collective. Kernel clock outputs are
    slot-local — use `local_clock_union` for those."""
    arr, _D, A = _pad_axes(clocks, mesh)
    sh = doc_actor_sharding(mesh)
    arr = jax.device_put(arr, sh)
    fn = jax.jit(
        lambda c: jnp.max(c, axis=0),
        in_shardings=sh,
        out_shardings=NamedSharding(mesh, P("sp")),
    )
    with mesh:
        return fn(arr)[:A]


def sharded_dominated(clocks, query, mesh: Mesh):
    """[D, A], [A] -> [D] bool: which docs' clocks the query dominates.
    The actor-axis `all` reduction crosses sp shards."""
    import numpy as np

    arr, D, A = _pad_axes(clocks, mesh)
    q = np.zeros((arr.shape[1],), arr.dtype)
    q[:A] = np.asarray(query)
    csh = doc_actor_sharding(mesh)
    qsh = NamedSharding(mesh, P("sp"))
    arr = jax.device_put(arr, csh)
    q = jax.device_put(q, qsh)
    fn = jax.jit(
        lambda c, qq: jnp.all(c <= qq[None, :], axis=-1),
        in_shardings=(csh, qsh),
        out_shardings=NamedSharding(mesh, P("dp")),
    )
    with mesh:
        return fn(arr, q)[:D]


def local_clock_union(clock, doc_actors, n_actors: int, mesh: Mesh):
    """[D, A_loc] local-slot clocks + [D, A_loc] actor maps -> [n_actors]
    global union. The scatter-max crosses dp shards, so XLA lowers the
    replicated output to a max-allreduce over ICI."""
    rep = NamedSharding(mesh, P())
    fn = jax.jit(
        lambda c, da: jnp.zeros(n_actors + 1, jnp.int32)
        .at[jnp.where(da >= 0, da, n_actors).ravel()]
        .max(jnp.where(da >= 0, c, 0).ravel())[:n_actors],
        in_shardings=(doc_sharding(mesh), doc_sharding(mesh)),
        out_shardings=rep,
    )
    with mesh:
        return fn(clock, doc_actors)


class SlabRoundRobin:
    """Round-robin WHOLE slabs across visible devices with bounded
    per-device in-flight queues — the streaming pipeline's multi-chip
    dispatch (RepoBackend._dispatch_slab under HM_PIPELINE=1).

    Where `sharded_full` splits one slab across the mesh (dp sharding:
    one program, every chip in lockstep, the host blocked feeding all
    chips at once), round-robin keeps each slab whole on one chip and
    streams successive slabs to successive chips. Chips run independent
    programs, so while chip k computes slab N the host packs slab N+1
    for chip k+1 — the 8-chip projection becomes an actual overlapped
    run instead of an 8x divide of a serial device stage. Same kernels
    (materialize_full_device / the lean twin), same (A_loc, K) buckets,
    so results are bit-identical to the single-device and sharded
    paths.

    Backpressure: at most `depth` (HM_RR_DEPTH, default 2) unfetched
    slabs per device; dispatching onto a saturated device blocks on its
    OLDEST outstanding summary, which bounds host staging and device
    memory to depth x n_devices slabs."""

    def __init__(self, devices=None, depth: int = None) -> None:
        import os

        self.devices = list(
            devices if devices is not None else jax.devices()
        )
        self.depth = (
            depth
            if depth is not None
            else max(1, int(os.environ.get("HM_RR_DEPTH", "2")))
        )
        self._next = 0
        self._inflight = {i: [] for i in range(len(self.devices))}

    def dispatch(self, batch: ColumnarBatch, lean: bool = False):
        """(MaterializeOut, summary wire) on the next device in the
        cycle; blocks only when that device already holds `depth`
        unfetched slabs. The kernel entry is run_batch_full with a
        pinned device — the same code path as the single-device twin,
        so the two cannot diverge."""
        from ..ops.crdt_kernels import run_batch_full

        i = self._next
        self._next = (self._next + 1) % len(self.devices)
        q = self._inflight[i]
        while len(q) >= self.depth:
            q.pop(0).block_until_ready()
        out, summary = run_batch_full(
            batch, lean=lean, device=self.devices[i]
        )
        q.append(summary)
        return out, summary

    def drain(self) -> None:
        """Block until every outstanding dispatch has completed."""
        for q in self._inflight.values():
            while q:
                q.pop(0).block_until_ready()

    def release(self) -> None:
        """Drop the backpressure refs without blocking — called when a
        bulk load finishes dispatching. The consumers (pending summary
        entries / the fetch worker) hold their own refs; keeping these
        would pin depth x n_devices device buffers for the lifetime of
        the cached scheduler."""
        for q in self._inflight.values():
            q.clear()


def step(batch: ColumnarBatch, mesh: Mesh):
    """One full merge step: materialize everything + union every clock.
    This is the framework's 'training step' analogue — the complete
    device-side work of a bulk sync cycle."""
    out, doc_actors = _materialize_on_mesh(batch, mesh)
    union = local_clock_union(
        out.clock, doc_actors, max(1, len(batch.actors)), mesh
    )
    return out, union
