"""Multi-chip scale-out: mesh construction + sharded batched programs."""
