"""Message schemas: the three wire protocols.

Parity with the reference's protocol files (SURVEY.md §2.1):
- frontend <-> backend repo messages (reference src/RepoMsg.ts:6-158)
- connection handshake messages (reference src/NetworkMsg.ts:3-13)
- peer <-> peer doc messages (reference src/PeerMsg.ts:4-17)

All messages are plain dicts (JSON-serializable) with a "type" tag, so the
frontend/backend boundary can cross threads or processes unchanged — the
seam where the XLA bulk backend plugs in (SURVEY.md §7.1). Constructors
below are thin typed helpers; consumers dispatch on msg["type"].
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

# ---------------------------------------------------------------------------
# frontend -> backend


def create_msg(public_key: str, secret_key: str) -> Dict[str, Any]:
    return {"type": "Create", "publicKey": public_key, "secretKey": secret_key}


def open_msg(doc_id: str) -> Dict[str, Any]:
    return {"type": "Open", "id": doc_id}


def open_bulk_msg(doc_ids: List[str]) -> Dict[str, Any]:
    """Open many docs in one batched cold start (backend
    load_documents_bulk — the device slab path)."""
    return {"type": "OpenBulk", "ids": list(doc_ids)}


def request_msg(doc_id: str, request: Dict[str, Any]) -> Dict[str, Any]:
    """A local ChangeRequest (crdt.change.ChangeRequest.to_json())."""
    return {"type": "Request", "id": doc_id, "request": request}


def close_msg(doc_id: str) -> Dict[str, Any]:
    return {"type": "Close", "id": doc_id}


def destroy_msg(doc_id: str) -> Dict[str, Any]:
    return {"type": "Destroy", "id": doc_id}


def merge_msg(doc_id: str, actors: List[str]) -> Dict[str, Any]:
    """actors: clock strings ("<actor>:<seq>" | "<actor>")."""
    return {"type": "Merge", "id": doc_id, "actors": actors}


def needs_actor_msg(doc_id: str) -> Dict[str, Any]:
    return {"type": "NeedsActorId", "id": doc_id}


def doc_message_msg(doc_id: str, contents: Any) -> Dict[str, Any]:
    """Ephemeral app-level message routed to peers of a doc."""
    return {"type": "DocMessage", "id": doc_id, "contents": contents}


def query_msg(query_id: int, query: Dict[str, Any]) -> Dict[str, Any]:
    """Query/reply envelope (Materialize, Metadata — reference
    QueryMsg/ReplyMsg wrapping, src/RepoMsg.ts)."""
    return {"type": "Query", "queryId": query_id, "query": query}


def materialize_query(doc_id: str, history: int) -> Dict[str, Any]:
    return {"type": "Materialize", "id": doc_id, "history": history}


def metadata_query(url_id: str) -> Dict[str, Any]:
    return {"type": "Metadata", "id": url_id}


def read_query(doc_id: str, query: Dict[str, Any]) -> Dict[str, Any]:
    """A one-shot read against the serving tier (serve/tier.py
    READ_KINDS): answered from HBM-resident state under HM_SERVE=1,
    from per-request host materialization under HM_SERVE=0 —
    bit-identical payloads either way."""
    return {"type": "Read", "id": doc_id, "query": dict(query)}


def telemetry_query() -> Dict[str, Any]:
    """Process-wide telemetry snapshot (counters + trace state) from
    the backend — the live-introspection feed tools/top.py polls over
    the IPC/serve seam."""
    return {"type": "Telemetry"}


# ---------------------------------------------------------------------------
# backend -> frontend


def ready_msg(
    doc_id: str,
    actor_id: Optional[str],
    patch: Optional[Dict[str, Any]],
    history: int,
) -> Dict[str, Any]:
    return {
        "type": "Ready",
        "id": doc_id,
        "actorId": actor_id,
        "patch": patch,
        "history": history,
    }


def actor_id_msg(doc_id: str, actor_id: str) -> Dict[str, Any]:
    return {"type": "ActorId", "id": doc_id, "actorId": actor_id}


def patch_msg(
    doc_id: str, patch: Dict[str, Any], history: int
) -> Dict[str, Any]:
    return {"type": "Patch", "id": doc_id, "patch": patch, "history": history}


def doc_message_fwd_msg(doc_id: str, contents: Any) -> Dict[str, Any]:
    return {"type": "DocMessageFwd", "id": doc_id, "contents": contents}


def reply_msg(query_id: int, payload: Any) -> Dict[str, Any]:
    return {"type": "Reply", "queryId": query_id, "payload": payload}


def download_msg(
    doc_id: str, actor_id: str, index: int, size: int, elapsed_ms: float
) -> Dict[str, Any]:
    """Block-download progress (reference ActorBlockDownloadedMsg,
    src/RepoMsg.ts:146-153)."""
    return {
        "type": "Download",
        "id": doc_id,
        "actorId": actor_id,
        "index": index,
        "size": size,
        "time": elapsed_ms,
    }


def file_server_ready_msg(path: str) -> Dict[str, Any]:
    return {"type": "FileServerReady", "path": path}


def bulk_ready_msg(doc_ids: List[str]) -> Dict[str, Any]:
    """Bulk cold start finished: these docs are ready backend-side; a
    frontend opening one receives its Ready (with snapshot patch) then.
    Keeping the per-doc patch out of this message is the point — 10k
    snapshot decodes must not happen eagerly."""
    return {"type": "BulkReady", "ids": list(doc_ids)}


# ---------------------------------------------------------------------------
# connection handshake (reference src/NetworkMsg.ts)


def info_msg(peer_id: str) -> Dict[str, Any]:
    return {"type": "Info", "peerId": peer_id}


def confirm_connection_msg(connection_id: str) -> Dict[str, Any]:
    return {"type": "ConfirmConnection", "connectionId": connection_id}


# ---------------------------------------------------------------------------
# peer <-> peer (reference src/PeerMsg.ts)


def cursor_message(
    doc_id: str, cursors: Dict[str, Any], clocks: Dict[str, Any]
) -> Dict[str, Any]:
    """Cursor + clock gossip per doc (reference CursorMessage)."""
    return {
        "type": "CursorMessage",
        "id": doc_id,
        "cursors": cursors,
        "clocks": clocks,
    }


def document_message(doc_id: str, contents: Any) -> Dict[str, Any]:
    return {"type": "DocumentMessage", "id": doc_id, "contents": contents}
