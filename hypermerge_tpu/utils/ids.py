"""Branded id types and id<->url codecs.

Maps reference src/Misc.ts:6-57: RepoId/DocId/ActorId/HyperfileId are all
base58 public keys with distinct roles; urls are `hypermerge:/<docId>` and
`hyperfile:/<hyperfileId>`. `root_actor_id(doc_id) == doc_id` — the document
id doubles as its root actor's feed key (reference src/Misc.ts:51-53).

Python has no nominal branded strings; we use NewType aliases for static
clarity and runtime validator functions (reference src/Metadata.ts:83-121
validateURL/validateDocURL/validateFileURL).
"""

from __future__ import annotations

from typing import NewType, Tuple, Union

from . import base58

RepoId = NewType("RepoId", str)
DocId = NewType("DocId", str)
ActorId = NewType("ActorId", str)
HyperfileId = NewType("HyperfileId", str)
DiscoveryId = NewType("DiscoveryId", str)
DocUrl = NewType("DocUrl", str)
HyperfileUrl = NewType("HyperfileUrl", str)

DOC_SCHEME = "hypermerge"
FILE_SCHEME = "hyperfile"


def is_base58_key(s: str) -> bool:
    try:
        return len(base58.decode(s)) == 32
    except ValueError:
        return False


def to_doc_url(doc_id: str) -> DocUrl:
    return DocUrl(f"{DOC_SCHEME}:/{doc_id}")


def to_hyperfile_url(file_id: str) -> HyperfileUrl:
    return HyperfileUrl(f"{FILE_SCHEME}:/{file_id}")


def parse_url(url: str) -> Tuple[str, str]:
    """Returns (scheme, id). Raises ValueError on malformed urls."""
    scheme, sep, rest = url.partition(":/")
    if not sep or not rest or "/" in rest:
        raise ValueError(f"invalid url: {url!r}")
    if not is_base58_key(rest):
        raise ValueError(f"url id is not a valid key: {url!r}")
    return scheme, rest


def validate_url(url: str) -> Tuple[str, str]:
    scheme, id_ = parse_url(url)
    if scheme not in (DOC_SCHEME, FILE_SCHEME):
        raise ValueError(f"unknown url scheme: {url!r}")
    return scheme, id_


def validate_doc_url(url: Union[str, DocUrl]) -> DocId:
    scheme, id_ = parse_url(url)
    if scheme != DOC_SCHEME:
        raise ValueError(f"not a document url: {url!r}")
    return DocId(id_)


def validate_file_url(url: Union[str, HyperfileUrl]) -> HyperfileId:
    scheme, id_ = parse_url(url)
    if scheme != FILE_SCHEME:
        raise ValueError(f"not a hyperfile url: {url!r}")
    return HyperfileId(id_)


def url_to_id(url: str) -> str:
    return parse_url(url)[1]


def is_doc_url(url: str) -> bool:
    try:
        validate_doc_url(url)
        return True
    except ValueError:
        return False


def is_file_url(url: str) -> bool:
    try:
        validate_file_url(url)
        return True
    except ValueError:
        return False


def root_actor_id(doc_id: DocId) -> ActorId:
    """The document id IS its root actor's feed public key."""
    return ActorId(str(doc_id))


def get_or_create(mapping, key, factory):
    """dict.setdefault with a lazy factory (reference src/Misc.ts:76-93)."""
    try:
        return mapping[key]
    except KeyError:
        value = factory(key)
        mapping[key] = value
        return value
