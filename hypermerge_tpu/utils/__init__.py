"""Host-side utility primitives (queues, id codecs, logging)."""
