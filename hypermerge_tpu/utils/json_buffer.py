"""JSON <-> bytes helpers (reference src/JsonBuffer.ts:1-22).

`parse_all_valid` mirrors the reference's corrupt-ledger tolerance: invalid
entries are skipped, not fatal (reference src/JsonBuffer.ts:11-22) — part of
the failure-tolerance story (SURVEY.md §5).
"""

from __future__ import annotations

import json
from typing import Any, Iterable, List


def bufferify(obj: Any) -> bytes:
    return json.dumps(obj, separators=(",", ":"), sort_keys=True).encode("utf-8")


def parse(data: bytes) -> Any:
    return json.loads(data.decode("utf-8"))


def parse_all_valid(buffers: Iterable[bytes]) -> List[Any]:
    out: List[Any] = []
    for buf in buffers:
        try:
            out.append(parse(buf))
        except (ValueError, UnicodeDecodeError):
            continue
    return out
