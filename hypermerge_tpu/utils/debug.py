"""Namespaced debug logging + micro-bench timers.

Mirrors the reference's observability story (SURVEY.md §5): the `debug`
library with per-component namespaces gated by the DEBUG env var (reference
src/Debug.ts:1-8, src/RepoBackend.ts:42), plus per-apply wall-clock timers
(reference src/DocBackend.ts:207-212). Timers additionally aggregate into a
process-wide registry that bench.py reads.
"""

from __future__ import annotations

import fnmatch
import os
import re
import sys
import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Any, Callable, Dict, Iterator, Tuple

from ..analysis.lockdep import make_lock

# Patterns re-resolve at CALL time, not import time: a daemon can
# toggle namespaces without a restart, either programmatically
# (set_patterns) or by mutating os.environ["DEBUG"] — the env string
# is compared each call (one dict lookup) and only re-parsed on
# change. set_patterns() overrides the env until set_patterns(None).
_env_cache: str = ""
_env_patterns: list = []
_override: "list | None" = None
_patterns_lock = make_lock("util.debug")


def _parse(spec: str) -> list:
    return [p for p in re.split(r"[\s,]+", spec) if p]


def set_patterns(spec=None) -> None:
    """Set the active DEBUG patterns at runtime. ``spec`` is a
    DEBUG-style string ("live,net:*") or an iterable of patterns;
    ``None`` returns control to the DEBUG env var."""
    global _override
    if spec is None:
        _override = None
    elif isinstance(spec, str):
        _override = _parse(spec)
    else:
        _override = [str(p) for p in spec]


def _current_patterns() -> list:
    if _override is not None:
        return _override
    global _env_cache, _env_patterns
    env = os.environ.get("DEBUG", "")
    if env != _env_cache:
        with _patterns_lock:
            if env != _env_cache:
                _env_patterns = _parse(env)
                _env_cache = env
    return _env_patterns


def enabled(namespace: str) -> bool:
    return any(
        fnmatch.fnmatch(namespace, pat) for pat in _current_patterns()
    )


def log(namespace: str, *args: Any) -> None:
    if enabled(namespace):
        print(f"[{namespace}]", *args, file=sys.stderr)


def trace(label: str) -> Callable[..., Any]:
    """Logging combinator: returns a fn that logs its args and returns the
    first one (reference src/Debug.ts trace)."""

    def _trace(first: Any = None, *rest: Any) -> Any:
        log("trace", label, first, *rest)
        return first

    return _trace


# -- timers ----------------------------------------------------------------

_TIMINGS: Dict[str, Tuple[int, float]] = defaultdict(lambda: (0, 0.0))
_TIMINGS_LOCK = make_lock("util.debug")


@contextmanager
def bench(label: str) -> Iterator[None]:
    """Wall-clock one section; aggregates (count, total_seconds) per label
    (reference src/DocBackend.ts:207-212 logs per-apply ms; we also keep a
    cumulative registry like src/Metadata.ts:244-251)."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        with _TIMINGS_LOCK:
            count, total = _TIMINGS[label]
            _TIMINGS[label] = (count + 1, total + dt)
        log("bench", f"{label}: {dt * 1e3:.3f}ms")


def timings() -> Dict[str, Tuple[int, float]]:
    return dict(_TIMINGS)


def reset_timings() -> None:
    _TIMINGS.clear()
