"""Base58 (bitcoin alphabet) codec for key/id encoding.

The reference encodes all public keys / document ids as base58 strings via the
`bs58` npm package (reference src/Keys.ts:22-60). Implemented from the well
known alphabet definition; no external dependency.
"""

from __future__ import annotations

_ALPHABET = "123456789ABCDEFGHJKLMNPQRSTUVWXYZabcdefghijkmnopqrstuvwxyz"
_INDEX = {c: i for i, c in enumerate(_ALPHABET)}


def encode(data: bytes) -> str:
    n = int.from_bytes(data, "big")
    out = []
    while n > 0:
        n, rem = divmod(n, 58)
        out.append(_ALPHABET[rem])
    # leading zero bytes -> leading '1's
    pad = 0
    for b in data:
        if b == 0:
            pad += 1
        else:
            break
    return "1" * pad + "".join(reversed(out))


def decode(text: str) -> bytes:
    n = 0
    for c in text:
        try:
            n = n * 58 + _INDEX[c]
        except KeyError:
            raise ValueError(f"invalid base58 character {c!r}") from None
    raw = n.to_bytes((n.bit_length() + 7) // 8, "big") if n else b""
    pad = 0
    for c in text:
        if c == "1":
            pad += 1
        else:
            break
    return b"\x00" * pad + raw
