"""Keypair creation + base58 encoding + discovery keys.

Maps reference src/Keys.ts:22-60 (create/encode/decode/encodePair/decodePair,
discoveryKey). Discovery key = BLAKE2b-32 keyed hash of the public key with a
fixed context string, matching hypercore's scheme in shape (the exact context
differs — this framework defines its own wire identity).
"""

from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass
from typing import Optional

from . import base58, crypto

_DISCOVERY_CONTEXT = b"hypermerge-tpu"


@dataclass(frozen=True)
class KeyPair:
    public_key: str  # base58
    secret_key: Optional[str]  # base58 seed, None for readonly


@dataclass(frozen=True)
class KeyBuffer:
    public_key: bytes
    secret_key: Optional[bytes]


def create_buffer(seed: Optional[bytes] = None) -> KeyBuffer:
    seed = seed if seed is not None else os.urandom(32)
    return KeyBuffer(public_key=crypto.public_key(seed), secret_key=seed)


def create(seed: Optional[bytes] = None) -> KeyPair:
    return encode_pair(create_buffer(seed))


def encode(key: bytes) -> str:
    return base58.encode(key)


def decode(key: str) -> bytes:
    raw = base58.decode(key)
    if len(raw) != 32:
        raise ValueError(f"key must decode to 32 bytes, got {len(raw)}")
    return raw


def encode_pair(pair: KeyBuffer) -> KeyPair:
    return KeyPair(
        public_key=encode(pair.public_key),
        secret_key=base58.encode(pair.secret_key) if pair.secret_key else None,
    )


def decode_pair(pair: KeyPair) -> KeyBuffer:
    return KeyBuffer(
        public_key=decode(pair.public_key),
        secret_key=base58.decode(pair.secret_key) if pair.secret_key else None,
    )


def discovery_key(public_key: bytes) -> bytes:
    """Public-key-derived rendezvous id that does not reveal the key itself."""
    return hashlib.blake2b(
        _DISCOVERY_CONTEXT, key=public_key, digest_size=32
    ).digest()


def discovery_id(public_id: str) -> str:
    return encode(discovery_key(decode(public_id)))
