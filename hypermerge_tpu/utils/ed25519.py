"""Ed25519 signatures (RFC 8032), pure Python.

The reference gets ed25519 keypairs/signatures from hypercore-crypto ->
sodium-native (reference src/Keys.ts:2-5, package.json resolutions). This
implementation is written directly from the RFC 8032 specification so the
framework has zero external crypto dependencies; the hot path (feed appends)
signs batched merkle roots, not individual blocks, so pure-Python throughput
is acceptable. A C++ implementation can replace this behind the same API.
"""

from __future__ import annotations

import hashlib
from typing import Tuple

_P = 2**255 - 19
_L = 2**252 + 27742317777372353535851937790883648493
_D = (-121665 * pow(121666, _P - 2, _P)) % _P
_I = pow(2, (_P - 1) // 4, _P)


def _sha512(data: bytes) -> bytes:
    return hashlib.sha512(data).digest()


def _inv(x: int) -> int:
    return pow(x, _P - 2, _P)


def _xrecover(y: int) -> int:
    xx = (y * y - 1) * _inv(_D * y * y + 1)
    x = pow(xx, (_P + 3) // 8, _P)
    if (x * x - xx) % _P != 0:
        x = (x * _I) % _P
    if x % 2 != 0:
        x = _P - x
    return x


_BY = (4 * _inv(5)) % _P
_BX = _xrecover(_BY)
_B = (_BX % _P, _BY % _P, 1, (_BX * _BY) % _P)  # extended coords
_IDENT = (0, 1, 1, 0)


def _edwards_add(p: Tuple[int, int, int, int], q: Tuple[int, int, int, int]):
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = ((y1 - x1) * (y2 - x2)) % _P
    b = ((y1 + x1) * (y2 + x2)) % _P
    c = (t1 * 2 * _D * t2) % _P
    dd = (z1 * 2 * z2) % _P
    e = b - a
    f = dd - c
    g = dd + c
    h = b + a
    return ((e * f) % _P, (g * h) % _P, (f * g) % _P, (e * h) % _P)


def _scalarmult(p: Tuple[int, int, int, int], e: int):
    q = _IDENT
    while e > 0:
        if e & 1:
            q = _edwards_add(q, p)
        p = _edwards_add(p, p)
        e >>= 1
    return q


def _compress(p: Tuple[int, int, int, int]) -> bytes:
    x, y, z, _ = p
    zi = _inv(z)
    x, y = (x * zi) % _P, (y * zi) % _P
    return int.to_bytes(y | ((x & 1) << 255), 32, "little")


def _decompress(s: bytes) -> Tuple[int, int, int, int]:
    n = int.from_bytes(s, "little")
    y = n & ((1 << 255) - 1)
    sign = n >> 255
    if y >= _P:  # RFC 8032 §5.1.3: non-canonical y must fail
        raise ValueError("non-canonical point encoding")
    x = _xrecover(y)
    if x == 0 and sign == 1:  # -0 is not a valid encoding
        raise ValueError("non-canonical point encoding")
    if x & 1 != sign:
        x = _P - x
    if (-x * x + y * y - 1 - _D * x * x * y * y) % _P != 0:
        raise ValueError("invalid point encoding")
    return (x, y, 1, (x * y) % _P)


def _clamp(h: bytes) -> int:
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a


def public_key(seed: bytes) -> bytes:
    if len(seed) != 32:
        raise ValueError("seed must be 32 bytes")
    a = _clamp(_sha512(seed))
    return _compress(_scalarmult(_B, a))


def sign(message: bytes, seed: bytes, pub: bytes | None = None) -> bytes:
    if pub is None:
        pub = public_key(seed)
    h = _sha512(seed)
    a = _clamp(h)
    r = int.from_bytes(_sha512(h[32:] + message), "little") % _L
    rp = _compress(_scalarmult(_B, r))
    k = int.from_bytes(_sha512(rp + pub + message), "little") % _L
    s = (r + k * a) % _L
    return rp + int.to_bytes(s, 32, "little")


def verify(message: bytes, signature: bytes, pub: bytes) -> bool:
    if len(signature) != 64 or len(pub) != 32:
        return False
    try:
        a_point = _decompress(pub)
        r_point = _decompress(signature[:32])
    except ValueError:
        return False
    s = int.from_bytes(signature[32:], "little")
    if s >= _L:
        return False
    k = int.from_bytes(_sha512(signature[:32] + pub + message), "little") % _L
    left = _scalarmult(_B, s)
    right = _edwards_add(r_point, _scalarmult(a_point, k))
    # compare affine coords
    x1, y1, z1, _ = left
    x2, y2, z2, _ = right
    return (x1 * z2 - x2 * z1) % _P == 0 and (y1 * z2 - y2 * z1) % _P == 0
