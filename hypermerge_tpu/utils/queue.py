"""Single-subscriber buffering queue — the universal async primitive.

Semantics match the reference's Queue (reference src/Queue.ts:3-73): items
pushed before a subscriber exists are buffered; `subscribe` first drains the
buffer then turns `push` into a direct call; a second concurrent subscriber is
an error (this is the structural race-avoidance device the whole runtime leans
on, reference src/Queue.ts:39-41).

Unlike the reference we are not on a single-threaded event loop, so the drain
and the direct-call handoff are guarded by a lock; the guarantee provided is
that callbacks for one queue are never run concurrently and never reordered.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Deque, Generic, List, Optional, TypeVar

from ..analysis import lockdep
from ..analysis.lockdep import make_rlock
from .debug import log

T = TypeVar("T")


class Queue(Generic[T]):
    def __init__(self, name: str = "q") -> None:
        self.name = name
        self._buffer: Deque[T] = deque()
        self._subscription: Optional[Callable[[T], None]] = None
        self._lock = make_rlock("util.queue")
        self._draining = False
        self._first_waiters: List[threading.Event] = []
        self._has_first = False
        self._first_value: Optional[T] = None

    @property
    def length(self) -> int:
        with self._lock:
            return len(self._buffer)

    def push(self, item: T) -> None:
        with self._lock:
            self._buffer.append(item)
            self._signal_first(item)
        self._drain()

    def subscribe(self, subscriber: Callable[[T], None]) -> None:
        with self._lock:
            if self._subscription is not None:
                raise RuntimeError(
                    f"queue {self.name!r} already has a subscriber"
                )
            log("queue:%s" % self.name, "subscribe")
            self._subscription = subscriber
        self._drain()

    def unsubscribe(self) -> None:
        with self._lock:
            self._subscription = None

    def once(self, subscriber: Callable[[T], None]) -> None:
        """Subscribe for exactly one item, then unsubscribe."""

        def one(item: T) -> None:
            self.unsubscribe()
            subscriber(item)

        self.subscribe(one)

    def first(self, timeout: Optional[float] = None) -> T:
        """Block until the first item is available and return it (does not
        consume — mirrors the promise-shaped `first()` of the reference,
        src/Queue.ts:16-20)."""
        with lockdep.blocking("queue_first", self.name):
            ev = threading.Event()
            with self._lock:
                if self._has_first:
                    return self._first_value  # type: ignore[return-value]
                self._first_waiters.append(ev)
            if not ev.wait(timeout):
                raise TimeoutError(
                    f"queue {self.name!r} first() timed out"
                )
            return self._first_value  # type: ignore[return-value]

    def drain(self) -> List[T]:
        with self._lock:
            items = list(self._buffer)
            self._buffer.clear()
            return items

    # -- internals ---------------------------------------------------------

    def _signal_first(self, item: T) -> None:
        if not self._has_first:
            self._has_first = True
            self._first_value = item
            for ev in self._first_waiters:
                ev.set()
            self._first_waiters.clear()

    def _drain(self) -> None:
        # Subscriber callbacks run OUTSIDE the lock (a subscriber may push
        # to other queues, or this one reentrantly). The _draining flag makes
        # exactly one thread the drainer at a time, preserving order and the
        # never-concurrent callback guarantee without holding the lock
        # across user code.
        while True:
            with self._lock:
                if (
                    self._draining
                    or not self._buffer
                    or self._subscription is None
                ):
                    return
                self._draining = True
                item = self._buffer.popleft()
                subscriber = self._subscription
            try:
                subscriber(item)
            finally:
                with self._lock:
                    self._draining = False
