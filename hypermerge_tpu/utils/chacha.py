"""Pure-Python X25519 + ChaCha20-Poly1305-IETF — transport-crypto fallback.

Used by net/secure.py when the native layer (libsodium via native/)
didn't load. Implements RFC 7748 (X25519 montgomery ladder) and RFC 8439
(ChaCha20, Poly1305, AEAD construction) exactly, so pure and native
endpoints interoperate on the wire. Slow (~1 MB/s) but correct; real
deployments get the C path.
"""

from __future__ import annotations

import hmac
import struct
from typing import Optional

# ---------------------------------------------------------------------------
# X25519 (RFC 7748)

_P = 2**255 - 19
_A24 = 121665


def x25519(k: bytes, u: bytes) -> bytes:
    kb = bytearray(k[:32])
    kb[0] &= 248
    kb[31] &= 127
    kb[31] |= 64
    scalar = int.from_bytes(kb, "little")
    x1 = int.from_bytes(u[:32], "little") & ((1 << 255) - 1)
    x2, z2, x3, z3 = 1, 0, x1, 1
    swap = 0
    for t in reversed(range(255)):
        k_t = (scalar >> t) & 1
        swap ^= k_t
        if swap:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        swap = k_t
        a = (x2 + z2) % _P
        aa = a * a % _P
        b = (x2 - z2) % _P
        bb = b * b % _P
        e = (aa - bb) % _P
        c = (x3 + z3) % _P
        d = (x3 - z3) % _P
        da = d * a % _P
        cb = c * b % _P
        x3 = (da + cb) % _P
        x3 = x3 * x3 % _P
        z3 = (da - cb) % _P
        z3 = z3 * z3 % _P
        z3 = z3 * x1 % _P
        x2 = aa * bb % _P
        z2 = e * (aa + _A24 * e) % _P
    if swap:
        x2, x3 = x3, x2
        z2, z3 = z3, z2
    return (x2 * pow(z2, _P - 2, _P) % _P).to_bytes(32, "little")


def x25519_base(sk: bytes) -> bytes:
    return x25519(sk, (9).to_bytes(32, "little"))


# ---------------------------------------------------------------------------
# ChaCha20 (RFC 8439)


def _rotl(v: int, n: int) -> int:
    return ((v << n) | (v >> (32 - n))) & 0xFFFFFFFF


def _chacha20_block(key: bytes, counter: int, nonce: bytes) -> bytes:
    state = list(
        struct.unpack(
            "<16I",
            b"expand 32-byte k" + key + struct.pack("<I", counter) + nonce,
        )
    )
    w = list(state)

    def qr(a, b, c, d):
        w[a] = (w[a] + w[b]) & 0xFFFFFFFF
        w[d] = _rotl(w[d] ^ w[a], 16)
        w[c] = (w[c] + w[d]) & 0xFFFFFFFF
        w[b] = _rotl(w[b] ^ w[c], 12)
        w[a] = (w[a] + w[b]) & 0xFFFFFFFF
        w[d] = _rotl(w[d] ^ w[a], 8)
        w[c] = (w[c] + w[d]) & 0xFFFFFFFF
        w[b] = _rotl(w[b] ^ w[c], 7)

    for _ in range(10):
        qr(0, 4, 8, 12)
        qr(1, 5, 9, 13)
        qr(2, 6, 10, 14)
        qr(3, 7, 11, 15)
        qr(0, 5, 10, 15)
        qr(1, 6, 11, 12)
        qr(2, 7, 8, 13)
        qr(3, 4, 9, 14)
    return struct.pack(
        "<16I", *((w[i] + state[i]) & 0xFFFFFFFF for i in range(16))
    )


def _chacha20_xor(
    key: bytes, counter: int, nonce: bytes, data: bytes
) -> bytes:
    out = bytearray(len(data))
    for i in range(0, len(data), 64):
        block = _chacha20_block(key, counter + i // 64, nonce)
        chunk = data[i : i + 64]
        out[i : i + len(chunk)] = bytes(
            x ^ y for x, y in zip(chunk, block)
        )
    return bytes(out)


# ---------------------------------------------------------------------------
# Poly1305 (RFC 8439)


def _poly1305(key: bytes, msg: bytes) -> bytes:
    r = int.from_bytes(key[:16], "little") & 0x0FFFFFFC0FFFFFFC0FFFFFFC0FFFFFFF
    s = int.from_bytes(key[16:32], "little")
    p = (1 << 130) - 5
    acc = 0
    for i in range(0, len(msg), 16):
        block = msg[i : i + 16]
        n = int.from_bytes(block + b"\x01", "little")
        acc = (acc + n) * r % p
    return ((acc + s) & ((1 << 128) - 1)).to_bytes(16, "little")


def _pad16(data: bytes) -> bytes:
    return data + b"\x00" * ((-len(data)) % 16)


# ---------------------------------------------------------------------------
# AEAD construction (RFC 8439 §2.8, no associated data)


def aead_encrypt(key: bytes, nonce: bytes, msg: bytes) -> bytes:
    otk = _chacha20_block(key, 0, nonce)[:32]
    ct = _chacha20_xor(key, 1, nonce, msg)
    mac_data = _pad16(ct) + struct.pack("<QQ", 0, len(ct))
    return ct + _poly1305(otk, mac_data)


def aead_decrypt(key: bytes, nonce: bytes, data: bytes) -> Optional[bytes]:
    """Plaintext, or None when authentication fails."""
    if len(data) < 16:
        return None
    ct, tag = data[:-16], data[-16:]
    otk = _chacha20_block(key, 0, nonce)[:32]
    mac_data = _pad16(ct) + struct.pack("<QQ", 0, len(ct))
    if not hmac.compare_digest(_poly1305(otk, mac_data), tag):
        return None
    return _chacha20_xor(key, 1, nonce, ct)
