"""Map-of-sets helper with reverse lookup (reference src/MapSet.ts:1-64)."""

from __future__ import annotations

from typing import Dict, Generic, Iterable, Iterator, List, Set, Tuple, TypeVar

A = TypeVar("A")
B = TypeVar("B")


class MapSet(Generic[A, B]):
    def __init__(self) -> None:
        self._map: Dict[A, Set[B]] = {}

    def add(self, key: A, value: B) -> bool:
        s = self._map.setdefault(key, set())
        if value in s:
            return False
        s.add(value)
        return True

    def merge(self, key: A, values: Iterable[B]) -> None:
        self._map.setdefault(key, set()).update(values)

    def delete(self, key: A) -> None:
        self._map.pop(key, None)

    def remove(self, key: A, value: B) -> None:
        s = self._map.get(key)
        if s is not None:
            s.discard(value)
            if not s:
                del self._map[key]

    def get(self, key: A) -> Set[B]:
        return self._map.get(key, set())

    def has(self, key: A, value: B) -> bool:
        return value in self._map.get(key, ())

    def keys(self) -> List[A]:
        return list(self._map.keys())

    def keys_with(self, value: B) -> List[A]:
        """All keys whose set contains `value` (reference MapSet.keysWith)."""
        return [k for k, s in self._map.items() if value in s]

    def __iter__(self) -> Iterator[Tuple[A, Set[B]]]:
        return iter(self._map.items())

    def __len__(self) -> int:
        return len(self._map)
