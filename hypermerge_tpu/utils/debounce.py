"""Keyed debouncer: coalesce bursts of per-key events into one flush.

Used for idempotent latest-state broadcasts (cursor/clock gossip,
inbound-sync application — backend/repo_backend.py) and for the
replication live tail (net/replication.py), which marks keys with a
VALUE (the earliest dirty block offset) merged across a burst.

Semantics:
- flush_fn(batch) receives a dict {key: value}; marks landing during
  the window (or while a flush is running) join the next flush.
- flush_fn runs on one daemon thread, never concurrently with itself.
- close() drains: everything marked before close is flushed before the
  thread exits (an orderly shutdown loses nothing).
- With max_window_s set the window ADAPTS: when a flush takes longer
  than the floor window (sustained load), the next window stretches to
  the flush duration so batches grow instead of flush count.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from ..analysis import lockdep
from ..analysis.lockdep import make_rlock
from .debug import log


class Debouncer:
    def __init__(
        self,
        flush_fn: Callable[[Dict], None],
        window_s: float = 0.002,
        max_window_s: Optional[float] = None,
        merge: Optional[Callable] = None,
        name: str = "debounce",
        eager: bool = False,
    ) -> None:
        self._fn = flush_fn
        self._window = window_s
        self._max_window = max_window_s
        self._merge = merge
        # work-conserving mode: a backlog that accumulated WHILE the
        # previous flush ran flushes immediately (the flush duration is
        # itself the batching window under sustained load); the idle
        # window only pads the leading edge of a burst. Right for flush
        # fns whose cost amortizes over batch size (the live tick);
        # wrong for pure rate-limiters (gossip).
        self._eager = eager
        self._lock = make_rlock("util.debounce")
        self._cv = threading.Condition(self._lock)
        self._keys: Dict = {}
        self._inflight: Dict = {}
        self._flushing = False
        self._closed = False
        self._name = name
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=name
        )
        self._thread.start()

    def mark(self, key, value=None) -> None:
        with self._cv:
            if self._closed:
                return
            if self._merge is not None and key in self._keys:
                value = self._merge(self._keys[key], value)
            self._keys[key] = value
            self._cv.notify()

    def pending(self) -> Dict:
        """Snapshot of everything marked but not yet durably flushed:
        the batch currently inside flush_fn plus keys awaiting the next
        window. Readers that consult the flush target directly overlay
        this to stay read-your-writes without blocking on the flusher."""
        with self._cv:
            if not self._inflight and not self._keys:
                return {}
            merged = dict(self._inflight)
            merged.update(self._keys)
            return merged

    def flush_now(self, timeout: float = 5.0) -> bool:
        """Block until everything currently marked has FINISHED
        flushing (not merely been picked up by the flusher). Returns
        False if the timeout expired with work still in flight, so
        callers whose next step assumes durability (destroy deleting
        rows a late flush would resurrect) can act on the failure."""
        with lockdep.blocking("flush_wait", self._name):
            deadline = time.monotonic() + timeout
            with self._cv:
                while self._keys or self._flushing:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return False
                    self._cv.wait(remaining)
        return True

    def close(self, timeout: float = 5.0) -> None:
        """Stop accepting marks and drain: pending keys are flushed
        before the flusher thread exits."""
        with lockdep.blocking("thread_join", self._name):
            with self._cv:
                self._closed = True
                self._cv.notify_all()
            self._thread.join(timeout)

    def _loop(self) -> None:
        last_flush = 0.0
        failures = 0
        while True:
            waited = False
            with self._cv:
                while not self._keys and not self._closed:
                    self._cv.wait()
                    waited = True
                    last_flush = 0.0  # quiet period: back to low latency
                if self._closed and not self._keys:
                    return
                closing = self._closed
            if self._eager and not waited and not closing:
                pass  # backlog from the last flush: no window, go now
            elif not closing:  # closing: drain immediately, no window
                window = self._window
                if self._max_window is not None:
                    window = max(
                        window, min(last_flush, self._max_window)
                    )
                if window > 0:
                    time.sleep(window)
            with self._cv:
                batch = self._keys
                self._keys = {}
                self._inflight = batch
                self._flushing = True
            t0 = time.perf_counter()
            try:
                self._fn(batch)
                failures = 0
            except Exception as e:  # pragma: no cover - defensive
                failures += 1
                log("debounce", f"{self._name} flush failed: {e}")
                with self._cv:
                    if failures < 8:
                        # a transient error (sqlite busy, disk full)
                        # must not LOSE the batch: re-queue it for
                        # retry. Keys re-marked during the failed flush
                        # are newer — they win (or merge on top).
                        for k, v in batch.items():
                            if k not in self._keys:
                                self._keys[k] = v
                            elif self._merge is not None:
                                self._keys[k] = self._merge(
                                    v, self._keys[k]
                                )
                    else:
                        log(
                            "debounce",
                            f"{self._name} dropping batch after "
                            f"{failures} consecutive failures",
                        )
            finally:
                last_flush = time.perf_counter() - t0
                with self._cv:
                    self._inflight = {}
                    self._flushing = False
                    self._cv.notify_all()
            if failures:
                # bounded backoff so a persistent error can't hot-spin
                # the flusher (close()'s join timeout still bounds exit)
                time.sleep(min(0.05 * failures, 0.5))
