"""Keyed debouncer: coalesce bursts of per-key events into one flush.

Used for idempotent latest-state broadcasts (cursor/clock gossip,
inbound-sync application — backend/repo_backend.py) and for the
replication live tail (net/replication.py), which marks keys with a
VALUE (the earliest dirty block offset) merged across a burst.

Semantics:
- flush_fn(batch) receives a dict {key: value}; marks landing during
  the window (or while a flush is running) join the next flush.
- flush_fn runs on one daemon thread, never concurrently with itself.
- close() drains: everything marked before close is flushed before the
  thread exits (an orderly shutdown loses nothing).
- With max_window_s set the window ADAPTS: when a flush takes longer
  than the floor window (sustained load), the next window stretches to
  the flush duration so batches grow instead of flush count.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from .debug import log


class Debouncer:
    def __init__(
        self,
        flush_fn: Callable[[Dict], None],
        window_s: float = 0.002,
        max_window_s: Optional[float] = None,
        merge: Optional[Callable] = None,
        name: str = "debounce",
    ) -> None:
        self._fn = flush_fn
        self._window = window_s
        self._max_window = max_window_s
        self._merge = merge
        self._lock = threading.RLock()
        self._cv = threading.Condition(self._lock)
        self._keys: Dict = {}
        self._flushing = False
        self._closed = False
        self._name = name
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=name
        )
        self._thread.start()

    def mark(self, key, value=None) -> None:
        with self._cv:
            if self._closed:
                return
            if self._merge is not None and key in self._keys:
                value = self._merge(self._keys[key], value)
            self._keys[key] = value
            self._cv.notify()

    def flush_now(self, timeout: float = 5.0) -> None:
        """Block until everything currently marked has FINISHED
        flushing (not merely been picked up by the flusher)."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._keys or self._flushing:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return
                self._cv.wait(remaining)

    def close(self, timeout: float = 5.0) -> None:
        """Stop accepting marks and drain: pending keys are flushed
        before the flusher thread exits."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout)

    def _loop(self) -> None:
        last_flush = 0.0
        while True:
            with self._cv:
                while not self._keys and not self._closed:
                    self._cv.wait()
                    last_flush = 0.0  # quiet period: back to low latency
                if self._closed and not self._keys:
                    return
                closing = self._closed
            if not closing:  # closing: drain immediately, no window
                window = self._window
                if self._max_window is not None:
                    window = max(
                        window, min(last_flush, self._max_window)
                    )
                if window > 0:
                    time.sleep(window)
            with self._cv:
                batch = self._keys
                self._keys = {}
                self._flushing = True
            t0 = time.perf_counter()
            try:
                self._fn(batch)
            except Exception as e:  # pragma: no cover - defensive
                log("debounce", f"{self._name} flush failed: {e}")
            finally:
                last_flush = time.perf_counter() - t0
                with self._cv:
                    self._flushing = False
                    self._cv.notify_all()
