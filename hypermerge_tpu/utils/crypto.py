"""Crypto facade: native (libsodium via native/) with pure-Python fallback.

The reference's crypto arrives through hypercore-crypto -> sodium-native
(reference src/Keys.ts:2-5); here the same primitives route through the
C++ native layer when it loaded, else the RFC 8032 implementation in
utils/ed25519.py. Signing throughput matters: feed integrity signs a
merkle root per append (storage/integrity.py), and pure-Python ed25519
costs ~ms per signature where sodium costs ~20µs.

blake2b stays on hashlib (already C, same libsodium algorithm); the
merkle tree has a native bulk path for many-leaf recomputes.
"""

from __future__ import annotations

import hashlib
from typing import List, Sequence

from .. import native
from . import ed25519 as _pure

_LEAF_PREFIX = b"\x00"
_NODE_PREFIX = b"\x01"


def public_key(seed: bytes) -> bytes:
    pub = native.ed25519_public(seed)
    return pub if pub is not None else _pure.public_key(seed)


def sign(message: bytes, seed: bytes) -> bytes:
    sig = native.ed25519_sign(seed, message)
    return sig if sig is not None else _pure.sign(message, seed)


def verify(message: bytes, signature: bytes, pub: bytes) -> bool:
    if len(signature) != 64 or len(pub) != 32:
        return False
    ok = native.ed25519_verify(pub, message, signature)
    return ok if ok is not None else _pure.verify(message, signature, pub)


def blake2b32(data: bytes) -> bytes:
    return hashlib.blake2b(data, digest_size=32).digest()


def leaf_hash(block: bytes) -> bytes:
    """Domain-separated leaf hash (0x00 prefix, second-preimage guard)."""
    return blake2b32(_LEAF_PREFIX + block)


def merkle_root(leaf_hashes: Sequence[bytes]) -> bytes:
    """Root over 32-byte leaf hashes: parent = blake2b32(0x01||l||r),
    an odd trailing node is promoted; 0 leaves -> 32 zero bytes. The
    native path computes the whole tree in C; the fallback is identical
    level-by-level Python."""
    if not leaf_hashes:
        return b"\x00" * 32
    concat = b"".join(leaf_hashes)
    root = native.merkle_root(concat)
    if root is not None:
        return root
    level: List[bytes] = list(leaf_hashes)
    while len(level) > 1:
        nxt = []
        for i in range(0, len(level) - 1, 2):
            nxt.append(blake2b32(_NODE_PREFIX + level[i] + level[i + 1]))
        if len(level) % 2 == 1:
            nxt.append(level[-1])
        level = nxt
    return level[0]
