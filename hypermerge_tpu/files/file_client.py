"""FileServerClient: frontend-side HTTP client of the file server.

Parity: reference src/FileServerClient.ts:8-59 — write/header/read over
the Unix-socket server the backend announced via FileServerReady.
"""

from __future__ import annotations

import http.client
import socket
from typing import Iterable, Tuple, Union

from ..utils import json_buffer
from ..utils.ids import validate_file_url
from .file_store import FileHeader


class _UnixHTTPConnection(http.client.HTTPConnection):
    def __init__(self, socket_path: str, timeout: float = 30.0) -> None:
        super().__init__("localhost", timeout=timeout)
        self._socket_path = socket_path

    def connect(self) -> None:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(self.timeout)
        sock.connect(self._socket_path)
        self.sock = sock


class FileServerClient:
    def __init__(self, socket_path: str) -> None:
        self.socket_path = socket_path

    def _conn(self) -> _UnixHTTPConnection:
        return _UnixHTTPConnection(self.socket_path)

    def write(
        self,
        data: Union[bytes, Iterable[bytes]],
        mime_type: str = "application/octet-stream",
    ) -> FileHeader:
        if not isinstance(data, (bytes, bytearray, memoryview)):
            data = b"".join(data)
        conn = self._conn()
        try:
            conn.request(
                "POST", "/", body=bytes(data), headers={"Content-Type": mime_type}
            )
            resp = conn.getresponse()
            body = resp.read()
            if resp.status != 200:
                raise IOError(f"upload failed ({resp.status}): {body!r}")
            return FileHeader.from_json(json_buffer.parse(body))
        finally:
            conn.close()

    def header(self, url: str) -> FileHeader:
        file_id = validate_file_url(url)
        conn = self._conn()
        try:
            conn.request("HEAD", f"/hyperfile:/{file_id}")
            resp = conn.getresponse()
            resp.read()
            if resp.status != 200:
                raise FileNotFoundError(url)
            return FileHeader(
                url=url,
                size=int(resp.headers["Content-Length"]),
                mime_type=resp.headers["Content-Type"],
                sha256=resp.headers["ETag"],
                blocks=int(resp.headers["X-Block-Count"]),
            )
        finally:
            conn.close()

    def read(self, url: str) -> Tuple[FileHeader, bytes]:
        file_id = validate_file_url(url)
        conn = self._conn()
        try:
            conn.request("GET", f"/hyperfile:/{file_id}")
            resp = conn.getresponse()
            body = resp.read()
            if resp.status != 200:
                raise FileNotFoundError(url)
            header = FileHeader(
                url=url,
                size=int(resp.headers["Content-Length"]),
                mime_type=resp.headers["Content-Type"],
                sha256=resp.headers["ETag"],
                blocks=int(resp.headers["X-Block-Count"]),
            )
            return header, body
        finally:
            conn.close()
