"""Hyperfile subsystem: write-once binary blobs as chunked feeds.

Parity target: reference src/FileStore.ts, src/FileServer.ts,
src/FileServerClient.ts, src/StreamLogic.ts (SURVEY.md §1.6, §3.6).
A file is its own feed: data blocks of at most MAX_BLOCK_SIZE bytes,
followed by ONE trailing JSON header block (size, mimeType, sha256) —
header last so readers can detect a complete upload.
"""

from .file_store import FileHeader, FileStore
from .stream_logic import MAX_BLOCK_SIZE, HashCounter, iter_chunks, rechunk

__all__ = [
    "FileHeader",
    "FileStore",
    "MAX_BLOCK_SIZE",
    "HashCounter",
    "iter_chunks",
    "rechunk",
]
