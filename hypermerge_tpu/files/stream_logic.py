"""Chunking + hashing primitives for the hyperfile write path.

Parity: reference src/StreamLogic.ts:4-63 — MaxChunkSizeTransform splits
oversized chunks while counting bytes/chunks; HashPassThrough computes a
sha256 while the data streams by. Node object streams become plain byte
iterators here; the transforms become generator combinators.
"""

from __future__ import annotations

import hashlib
from typing import Iterable, Iterator, Union

# Matches the reference's hyperfile chunk limit (src/FileStore.ts:10).
MAX_BLOCK_SIZE = 62 * 1024

Chunkable = Union[bytes, bytearray, memoryview, Iterable[bytes]]


def iter_chunks(data: Chunkable) -> Iterator[bytes]:
    """Normalize bytes-or-iterable-of-bytes into an iterator of bytes."""
    if isinstance(data, (bytes, bytearray, memoryview)):
        yield bytes(data)
        return
    for chunk in data:
        yield bytes(chunk)


def rechunk(
    chunks: Iterable[bytes], max_size: int = MAX_BLOCK_SIZE
) -> Iterator[bytes]:
    """Split any chunk larger than max_size; pass smaller chunks through
    unchanged (split-only, like MaxChunkSizeTransform — it never
    coalesces, reference src/StreamLogic.ts:20-38). Empty chunks are
    dropped."""
    if max_size <= 0:
        raise ValueError("max_size must be positive")
    for chunk in chunks:
        for start in range(0, len(chunk), max_size):
            yield chunk[start : start + max_size]


class HashCounter:
    """sha256 + byte/chunk counters updated as data streams through.

    Parity: HashPassThrough + the transform's byte/chunk counters
    (reference src/StreamLogic.ts:40-63)."""

    def __init__(self) -> None:
        self._hash = hashlib.sha256()
        self.bytes = 0
        self.chunks = 0

    def feed(self, chunk: bytes) -> bytes:
        self._hash.update(chunk)
        self.bytes += len(chunk)
        self.chunks += 1
        return chunk

    def wrap(self, chunks: Iterable[bytes]) -> Iterator[bytes]:
        for chunk in chunks:
            yield self.feed(chunk)

    @property
    def digest_hex(self) -> str:
        return self._hash.hexdigest()
