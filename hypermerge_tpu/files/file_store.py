"""FileStore: write-once binary blobs stored as chunked feeds.

Parity: reference src/FileStore.ts:20-80 — write chunks data at
MAX_BLOCK_SIZE, sha256s while streaming, and appends a JSON header block
LAST (so a feed whose tail parses as a header is a complete upload);
read streams every block except the trailing header; header reads just
the head block.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, Optional

from ..storage.feed import FeedStore
from ..utils import json_buffer
from ..utils import keys as keymod
from ..utils.ids import to_hyperfile_url, url_to_id
from ..utils.queue import Queue
from .stream_logic import MAX_BLOCK_SIZE, Chunkable, HashCounter, iter_chunks, rechunk


@dataclass(frozen=True)
class FileHeader:
    """The trailing header block (reference src/FileStore.ts:44-67:
    `{type: 'File', url, bytes, mimeType, sha256}`)."""

    url: str
    size: int
    mime_type: str
    sha256: str
    blocks: int  # data blocks, header excluded

    def to_json(self) -> dict:
        return {
            "type": "File",
            "url": self.url,
            "bytes": self.size,
            "mimeType": self.mime_type,
            "sha256": self.sha256,
            "blocks": self.blocks,
        }

    @staticmethod
    def from_json(obj: dict) -> "FileHeader":
        if obj.get("type") != "File":
            raise ValueError(f"not a file header: {obj!r}")
        return FileHeader(
            url=obj["url"],
            size=obj["bytes"],
            mime_type=obj["mimeType"],
            sha256=obj["sha256"],
            blocks=obj.get("blocks", -1),
        )


class FileStore:
    """Writes/reads hyperfiles over a FeedStore. Completed writes are
    announced on `write_log` (the backend's Metadata ledger subscribes —
    reference src/RepoBackend.ts:105-107)."""

    def __init__(self, feeds: FeedStore) -> None:
        self.feeds = feeds
        self.write_log: Queue = Queue("filestore:writelog")

    def write(self, data: Chunkable, mime_type: str) -> FileHeader:
        pair = keymod.create()
        feed = self.feeds.create(pair)
        counter = HashCounter()
        n_blocks = 0
        for chunk in counter.wrap(rechunk(iter_chunks(data), MAX_BLOCK_SIZE)):
            feed.append(chunk)
            n_blocks += 1
        header = FileHeader(
            url=to_hyperfile_url(pair.public_key),
            size=counter.bytes,
            mime_type=mime_type,
            sha256=counter.digest_hex,
            blocks=n_blocks,
        )
        feed.append(json_buffer.bufferify(header.to_json()))  # header LAST
        self.write_log.push(header)
        return header

    def _existing_feed(self, file_id: str):
        # open_if_present, not open_feed: a lookup for an unknown id must
        # not create (and forever register/announce) an empty feed, but a
        # feed persisted by a previous run must still be reachable.
        feed = self.feeds.open_if_present(file_id)
        if feed is None or feed.length == 0:
            raise FileNotFoundError(f"hyperfile {file_id} has no blocks")
        return feed

    def header(self, file_id: str) -> FileHeader:
        feed = self._existing_feed(file_id)
        try:
            return FileHeader.from_json(
                json_buffer.parse(feed.get(feed.length - 1))
            )
        except (ValueError, KeyError) as exc:
            # tail block isn't a header: incomplete upload or not a file
            raise FileNotFoundError(f"hyperfile {file_id}: {exc}") from exc

    def read(self, file_id: str) -> Iterator[bytes]:
        """Stream every data block (all blocks except the trailing
        header, reference src/FileStore.ts:33-36)."""
        feed = self._existing_feed(file_id)
        for i in range(feed.length - 1):
            yield feed.get(i)

    def read_bytes(self, file_id: str) -> bytes:
        return b"".join(self.read(file_id))

    @staticmethod
    def id_of(url: str) -> str:
        return url_to_id(url)
