"""FileStore: write-once binary blobs stored as chunked feeds.

Parity: reference src/FileStore.ts:20-80 — write chunks data at
MAX_BLOCK_SIZE, sha256s while streaming, and appends a JSON header block
LAST (so a feed whose tail parses as a header is a complete upload);
read streams every block except the trailing header; header reads just
the head block.

Remote fetch (reference src/FileStore.ts:33-36 +
src/ReplicationManager.ts:71-89 — file feeds replicate like any feed
and reads stream blocks as they arrive): a hyperfile URL carries the
feed public key, so `read(file_id, timeout=...)` opens the feed,
announces it to the swarm (the `announce` hook wired by RepoBackend),
and streams data blocks progressively as replication backfills them —
header-last means the trailing header doubles as the completion marker.
`subscribe_progress` surfaces per-block download progress.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Iterator, Optional

from ..storage.feed import FeedStore
from ..utils import json_buffer
from ..utils import keys as keymod
from ..utils.ids import to_hyperfile_url, url_to_id
from ..utils.queue import Queue
from .stream_logic import MAX_BLOCK_SIZE, Chunkable, HashCounter, iter_chunks, rechunk


@dataclass(frozen=True)
class FileHeader:
    """The trailing header block (reference src/FileStore.ts:44-67:
    `{type: 'File', url, bytes, mimeType, sha256}`)."""

    url: str
    size: int
    mime_type: str
    sha256: str
    blocks: int  # data blocks, header excluded

    def to_json(self) -> dict:
        return {
            "type": "File",
            "url": self.url,
            "bytes": self.size,
            "mimeType": self.mime_type,
            "sha256": self.sha256,
            "blocks": self.blocks,
        }

    @staticmethod
    def from_json(obj: dict) -> "FileHeader":
        if obj.get("type") != "File":
            raise ValueError(f"not a file header: {obj!r}")
        return FileHeader(
            url=obj["url"],
            size=obj["bytes"],
            mime_type=obj["mimeType"],
            sha256=obj["sha256"],
            blocks=obj.get("blocks", -1),
        )


class FileStore:
    """Writes/reads hyperfiles over a FeedStore. Completed writes are
    announced on `write_log` (the backend's Metadata ledger subscribes —
    reference src/RepoBackend.ts:105-107)."""

    def __init__(
        self,
        feeds: FeedStore,
        announce: Optional[Callable] = None,
        forget: Optional[Callable] = None,
        remote_capable: Optional[Callable[[], bool]] = None,
    ) -> None:
        self.feeds = feeds
        self.write_log: Queue = Queue("filestore:writelog")
        # called with each file feed we create or fetch so the owner
        # (RepoBackend) can join the swarm + announce for replication;
        # `forget` undoes that for a speculative feed that fetched
        # nothing; `remote_capable` says whether a fetch could even
        # succeed (a swarm is attached)
        self._announce = announce
        self._forget = forget
        self._remote_capable = remote_capable

    def remote_capable(self) -> bool:
        return (
            self._announce is not None
            and (self._remote_capable is None or self._remote_capable())
        )

    def write(self, data: Chunkable, mime_type: str) -> FileHeader:
        pair = keymod.create()
        feed = self.feeds.create(pair)
        if self._announce is not None:
            # announce at write START: peers stream blocks during the
            # upload; header-last marks completion for them too
            self._announce(feed)
        counter = HashCounter()
        n_blocks = 0
        for chunk in counter.wrap(rechunk(iter_chunks(data), MAX_BLOCK_SIZE)):
            feed.append(chunk)
            n_blocks += 1
        header = FileHeader(
            url=to_hyperfile_url(pair.public_key),
            size=counter.bytes,
            mime_type=mime_type,
            sha256=counter.digest_hex,
            blocks=n_blocks,
        )
        feed.append(json_buffer.bufferify(header.to_json()))  # header LAST
        self.write_log.push(header)
        return header

    def _existing_feed(self, file_id: str):
        # open_if_present, not open_feed: a lookup for an unknown id must
        # not create (and forever register/announce) an empty feed, but a
        # feed persisted by a previous run must still be reachable.
        feed = self.feeds.open_if_present(file_id)
        if feed is None or feed.length == 0:
            raise FileNotFoundError(f"hyperfile {file_id} has no blocks")
        return feed

    def header(self, file_id: str) -> FileHeader:
        feed = self._existing_feed(file_id)
        try:
            return FileHeader.from_json(
                json_buffer.parse(feed.get(feed.length - 1))
            )
        except (ValueError, KeyError) as exc:
            # tail block isn't a header: incomplete upload or not a file
            raise FileNotFoundError(f"hyperfile {file_id}: {exc}") from exc

    def read(self, file_id: str, timeout: float = 0.0) -> Iterator[bytes]:
        """Stream every data block (all blocks except the trailing
        header, reference src/FileStore.ts:33-36).

        timeout == 0: local-only — the feed must already hold a
        complete upload. timeout > 0: remote-capable — the feed is
        opened + announced to the swarm and data blocks stream
        PROGRESSIVELY as replication delivers them (backfill is
        contiguous-from-head, so block i is readable the moment it
        lands); the trailing header ends the stream. TimeoutError if
        the upload hasn't completed within `timeout` seconds."""
        if timeout <= 0:
            feed = self._existing_feed(file_id)
            for i in range(feed.length - 1):
                yield feed.get(i)
            return
        feed = self._remote_feed(file_id)
        deadline = time.monotonic() + timeout
        i = 0
        while True:
            if feed.length > i:
                block = feed.get(i)
                if feed.length == i + 1:
                    hdr = self._try_header(block)
                    if hdr is not None and hdr.blocks in (-1, i):
                        return  # trailing header: upload complete
                    if hdr is None:
                        yield block  # tail is plainly data: stream it
                        i += 1
                        continue
                    # parses as header but counts the wrong number of
                    # data blocks: a DATA block whose content happens
                    # to be header JSON — wait for the next block to
                    # disambiguate (a real upload always has one)
                else:
                    yield block
                    i += 1
                    continue
            if time.monotonic() > deadline:
                self._forget_if_empty(file_id)
                raise TimeoutError(
                    f"hyperfile {file_id}: incomplete after {timeout}s "
                    f"({feed.length} blocks)"
                )
            time.sleep(0.01)

    def read_bytes(self, file_id: str, timeout: float = 0.0) -> bytes:
        return b"".join(self.read(file_id, timeout=timeout))

    def _remote_feed(self, file_id: str):
        """Open (possibly empty) + announce a file feed so replication
        can pull it from whoever holds it."""
        feed = self.feeds.get_feed(file_id)
        if feed is None:
            feed = self.feeds.open_feed(file_id)
            if self._announce is not None:
                self._announce(feed)
        return feed

    def _forget_if_empty(self, file_id: str) -> None:
        """A speculative remote open that fetched NOTHING leaves no
        trace: a bogus-id lookup must not permanently register/announce
        a feed."""
        feed = self.feeds.get_feed(file_id)
        if (
            feed is not None
            and feed.length == 0
            and not feed._sparse
        ):
            self.feeds.remove(file_id)
            if self._forget is not None:
                self._forget(feed)

    @staticmethod
    def _try_header(block: bytes) -> Optional[FileHeader]:
        try:
            return FileHeader.from_json(json_buffer.parse(block))
        except (ValueError, KeyError):
            return None

    def header_wait(self, file_id: str, timeout: float) -> FileHeader:
        """The trailing header, waiting up to `timeout` seconds for the
        upload to finish replicating in."""
        feed = self._remote_feed(file_id)
        deadline = time.monotonic() + timeout
        while True:
            if feed.length > 0:
                hdr = self._try_header(feed.get(feed.length - 1))
                if hdr is not None and hdr.blocks in (
                    -1, feed.length - 1
                ):
                    return hdr
            if time.monotonic() > deadline:
                self._forget_if_empty(file_id)
                raise TimeoutError(
                    f"hyperfile {file_id}: no complete header after "
                    f"{timeout}s ({feed.length} blocks)"
                )
            time.sleep(0.01)

    def subscribe_progress(
        self, file_id: str, cb: Callable[[int, int], None]
    ) -> Callable[[], None]:
        """cb(blocks_so_far, bytes_so_far) per arriving block (the
        Download-progress analogue for hyperfiles). Counters start at
        the feed's CURRENT state, so a retry after a partial fetch
        reports true totals. Attaches BEFORE the feed is announced, so
        the first replicated block can't slip past the subscription.
        Returns an unsubscribe callable."""
        feed = self.feeds.get_feed(file_id)
        fresh = feed is None
        if fresh:
            feed = self.feeds.open_feed(file_id)
        state = {
            "blocks": feed.length,
            "bytes": sum(len(b) for b in feed.read_all()),
        }

        def on_append(_index: int, data: bytes) -> None:
            state["blocks"] += 1
            state["bytes"] += len(data)
            cb(state["blocks"], state["bytes"])

        feed.on_append(on_append)
        if state["blocks"]:
            cb(state["blocks"], state["bytes"])  # baseline for retries
        if fresh and self._announce is not None:
            self._announce(feed)
        return lambda: feed.off_append(on_append)

    @staticmethod
    def id_of(url: str) -> str:
        return url_to_id(url)
