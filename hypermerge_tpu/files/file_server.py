"""FileServer: HTTP over a Unix socket serving hyperfiles.

Parity: reference src/FileServer.ts:7-101 — `POST /` uploads a body and
replies with the file header JSON; `GET/HEAD /hyperfile:/<id>` serves the
blob with ETag=sha256, Content-Length, Content-Type=mimeType and
X-Block-Count headers (src/FileServer.ts:84-93). The socket path comes
from the repo (FileServerReady message), mirroring toIpcPath
(src/Misc.ts:120-129) — on this platform a plain Unix socket path.
"""

from __future__ import annotations

import os
import socketserver
import threading
from http.server import BaseHTTPRequestHandler
from typing import Optional

from ..utils import json_buffer
from ..utils.ids import validate_file_url
from .file_store import FileStore
from .stream_logic import MAX_BLOCK_SIZE


class _UnixHTTPServer(socketserver.ThreadingUnixStreamServer):
    daemon_threads = True
    allow_reuse_address = True


class _Handler(BaseHTTPRequestHandler):
    # Set per-server via type(); silences default stderr logging.
    store: FileStore = None  # type: ignore[assignment]
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # pragma: no cover - silence
        pass

    # BaseHTTPRequestHandler wants a client address tuple; over AF_UNIX
    # it's a string or empty — normalize so logging helpers don't choke.
    def address_string(self) -> str:  # pragma: no cover
        return "unix"

    def _body_chunks(self, length: int):
        remaining = length
        while remaining > 0:
            chunk = self.rfile.read(min(remaining, MAX_BLOCK_SIZE))
            if not chunk:
                # A short body means the client disconnected mid-upload.
                # Raising here aborts FileStore.write BEFORE the trailing
                # header block is appended, so the truncated feed is never
                # durably recorded as a complete file (header-last
                # completeness contract, reference src/FileStore.ts:38-67).
                raise ConnectionError(
                    f"client disconnected with {remaining} bytes unread"
                )
            remaining -= len(chunk)
            yield chunk

    def do_POST(self) -> None:
        length = int(self.headers.get("Content-Length", "0"))
        if self.path != "/":
            # drain the body so a keep-alive connection stays parseable
            try:
                for _ in self._body_chunks(length):
                    pass
            except ConnectionError:
                self.close_connection = True
                return
            self._error(404, "upload path is /")
            return
        mime = self.headers.get("Content-Type", "application/octet-stream")
        # stream straight into the chunked write path — never buffer the
        # whole upload in memory
        try:
            header = self.store.write(self._body_chunks(length), mime)
        except ConnectionError as exc:
            self.close_connection = True
            try:
                self._error(400, str(exc))
            except OSError:
                pass  # the socket is gone; nothing to tell the client
            return
        payload = json_buffer.bufferify(header.to_json())
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def do_GET(self) -> None:
        self._serve(send_body=True)

    def do_HEAD(self) -> None:
        self._serve(send_body=False)

    def _serve(self, send_body: bool) -> None:
        try:
            file_id = validate_file_url(self.path.lstrip("/"))
        except ValueError as exc:
            self._error(404, str(exc))
            return
        remote_wait = 0.0
        try:
            header = self.store.header(file_id)
        except (FileNotFoundError, KeyError, ValueError) as exc:
            # not held locally: a swarm-wired store can fetch it from a
            # peer (reference behavior — file feeds replicate like any
            # feed); bounded wait, then stream as blocks arrive
            remote_wait = float(
                os.environ.get("HM_FILE_FETCH_TIMEOUT_S", "15")
            )
            if not self.store.remote_capable() or remote_wait <= 0:
                self._error(404, str(exc))
                return
            try:
                header = self.store.header_wait(file_id, remote_wait)
            except TimeoutError as texc:
                self._error(404, str(texc))
                return
        self.send_response(200)
        self.send_header("Content-Type", header.mime_type)
        self.send_header("Content-Length", str(header.size))
        self.send_header("ETag", header.sha256)
        self.send_header("X-Block-Count", str(header.blocks))
        self.end_headers()
        if send_body:
            for chunk in self.store.read(file_id, timeout=remote_wait):
                self.wfile.write(chunk)

    def _error(self, code: int, message: str) -> None:
        body = json_buffer.bufferify({"error": message})
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        # HEAD responses carry headers only (RFC 9110 §9.3.2) — writing a
        # body would desync a keep-alive client's framing.
        if self.command != "HEAD":
            self.wfile.write(body)


class FileServer:
    def __init__(self, store: FileStore) -> None:
        self.store = store
        self._server: Optional[_UnixHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def listen(self, path: str) -> None:
        if os.path.exists(path):
            os.unlink(path)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        handler = type("BoundHandler", (_Handler,), {"store": self.store})
        self._server = _UnixHTTPServer(path, handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="file-server"
        )
        self._thread.start()

    @property
    def listening(self) -> bool:
        return self._server is not None

    def close(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            try:
                os.unlink(self._server.server_address)  # type: ignore[arg-type]
            except OSError:
                pass
            self._server = None
