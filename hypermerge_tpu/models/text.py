"""Text — a collaboratively editable character sequence (RGA of chars).

Parity: Automerge's Text type (reference re-exports, src/index.ts:9-12).
Materialized snapshots behave like strings; edits go through the change-fn
proxy (insert/delete by index). Device-side, text is just a list object
whose values are single-character strings — the RGA kernels don't care.
"""

from __future__ import annotations

from typing import Iterator, List


class Text:
    __slots__ = ("_chars",)

    def __init__(self, chars: "List[str] | str" = "") -> None:
        self._chars = list(chars)

    def __str__(self) -> str:
        return "".join(self._chars)

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return f"Text({str(self)!r})"

    def __len__(self) -> int:
        return len(self._chars)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return "".join(self._chars[i])
        return self._chars[i]

    def __iter__(self) -> Iterator[str]:
        return iter(self._chars)

    def __eq__(self, other) -> bool:
        if isinstance(other, Text):
            return self._chars == other._chars
        if isinstance(other, str):
            return str(self) == other
        return NotImplemented

    def __hash__(self) -> int:
        return hash(str(self))
