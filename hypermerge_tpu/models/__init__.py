"""Document value models: the rich CRDT value types a materialized doc
exposes (Counter, Text, Table) — the framework's 'model families'."""

from .counter import Counter  # noqa: F401
from .table import Table  # noqa: F401
from .text import Text  # noqa: F401

__all__ = ["Counter", "Text", "Table"]
