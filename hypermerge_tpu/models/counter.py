"""Counter — a mergeable increment-only-conflict-free integer.

Parity: Automerge's Counter datatype (the reference re-exports Automerge
value types, reference src/index.ts:9-12). Concurrent increments all apply;
concurrent `set` replaces the counter (increments on the replaced counter op
are discarded with it).
"""

from __future__ import annotations


class Counter(int):
    """Immutable snapshot of a counter value. Mutation happens through the
    change-fn proxy (`proxy.increment(key, n)`), not on this object."""

    datatype = "counter"

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return f"Counter({int(self)})"
