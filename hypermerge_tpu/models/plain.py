"""to_plain — render a materialized document as plain JSON-able data.

Display form for tools/examples: Text becomes a str, Counter an int,
Table a {id: row} dict. (Tests use their own *tagged* normalizer so
type identity stays assertable; this one is for humans and JSON.)
"""

from __future__ import annotations

from typing import Any

from .counter import Counter
from .table import Table
from .text import Text


def to_plain(v: Any) -> Any:
    if isinstance(v, Text):
        return str(v)
    if isinstance(v, Table):
        return {k: to_plain(v.by_id(k)) for k in v.ids}
    if isinstance(v, Counter):
        return int(v)
    if isinstance(v, dict):
        return {k: to_plain(x) for k, x in v.items()}
    if isinstance(v, list):
        return [to_plain(x) for x in v]
    return v
