"""Table — a collection of uuid-keyed rows.

Parity: Automerge's Table type (reference re-exports, src/index.ts:9-12).
CRDT-wise a table is a map whose keys are row ids and whose values are row
objects; this class is the materialized read view.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List


class Table:
    __slots__ = ("_rows",)

    def __init__(self, rows: "Dict[str, Any] | None" = None) -> None:
        self._rows = dict(rows or {})

    @property
    def ids(self) -> List[str]:
        return sorted(self._rows.keys())

    def by_id(self, row_id: str) -> Any:
        return self._rows.get(row_id)

    @property
    def count(self) -> int:
        return len(self._rows)

    @property
    def rows(self) -> List[Any]:
        return [self._rows[i] for i in self.ids]

    def __len__(self) -> int:
        return len(self._rows)

    def __iter__(self) -> Iterator[Any]:
        return iter(self.rows)

    def __eq__(self, other) -> bool:
        if isinstance(other, Table):
            return self._rows == other._rows
        return NotImplemented

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return f"Table({self._rows!r})"
