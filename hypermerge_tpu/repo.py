"""Repo — the facade binding one RepoFrontend and one RepoBackend.

Parity: reference src/Repo.ts:11-58 — wires the two halves with mutual
subscribe and re-exports their methods. Here both halves live in-process;
the message protocol between them is plain dicts, so either half can be
moved across a thread/process boundary without API changes (the
reference's stated design goal, README.md:160-184).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from .backend.repo_backend import RepoBackend
from .frontend.handle import Handle
from .frontend.repo_frontend import RepoFrontend
from .utils.ids import DocUrl


class Repo:
    def __init__(
        self, path: Optional[str] = None, memory: bool = False
    ) -> None:
        self.front = RepoFrontend()
        self.back = RepoBackend(path=path, memory=memory)
        self.front.subscribe(self.back.receive)
        self.back.subscribe(self.front.receive)

    # -- identity -------------------------------------------------------

    @property
    def id(self) -> str:
        return self.back.id

    # -- doc api (delegated to the frontend) ---------------------------

    def create(self, init: Optional[dict] = None) -> DocUrl:
        return self.front.create(init)

    def open(self, url: str) -> Handle:
        return self.front.open(url)

    def open_many(self, urls) -> list:
        """Batched cold open: one backend bulk load (device slabs for
        large counts), handles whose snapshots decode lazily on first
        read. THE way to bring a big repo up (BASELINE config 4)."""
        return self.front.open_many(urls)

    def doc(self, url: str, cb: Optional[Callable] = None) -> Any:
        return self.front.doc(url, cb)

    def read(
        self, url: str, query: dict, cb: Optional[Callable] = None
    ) -> Any:
        """One-shot read served WITHOUT materializing the doc
        host-side: under HM_SERVE=1 (default) the backend's serving
        tier answers from HBM-resident summary columns via batched
        device query kernels; HM_SERVE=0 is the bit-identical
        per-request host twin. Query kinds: {"kind": "text", "path":
        ["body"]}, {"kind": "lookup", "path": ["a", "b"]}, {"kind":
        "index", "path": ["list"], "index": 3}, {"kind": "len",
        "path": []}, {"kind": "clock"}, {"kind": "history"}."""
        return self.front.read(url, query, cb)

    def watch(self, url: str, cb: Callable[[Any, int], None]) -> Handle:
        return self.front.watch(url, cb)

    def change(
        self, url: str, fn: Callable[[Any], None], message: str = ""
    ) -> None:
        self.front.change(url, fn, message)

    def merge(
        self, url: str, target: str, timeout: Optional[float] = 30.0
    ) -> None:
        """Adopt `target`'s actors/clock into `url`. If the target is an
        unknown doc that never becomes ready, the pending merge expires
        after `timeout` seconds (logged; pass None to wait forever)."""
        self.front.merge(url, target, timeout=timeout)

    def fork(self, url: str) -> DocUrl:
        return self.front.fork(url)

    def materialize(
        self, url: str, history: int, cb: Callable[[Any], None]
    ) -> None:
        self.front.materialize(url, history, cb)

    def meta(self, url: str, cb: Callable[[Any], None]) -> None:
        self.front.meta(url, cb)

    def telemetry(self, cb: Callable[[Any], None]) -> None:
        """Backend telemetry snapshot (see RepoFrontend.telemetry)."""
        self.front.telemetry(cb)

    def message(self, url: str, contents: Any) -> None:
        self.front.message(url, contents)

    def close_doc(self, url: str) -> None:
        self.front.close_doc(url)

    def destroy(self, url: str) -> None:
        self.front.destroy(url)

    def debug(self, url: str) -> dict:
        return self.front.debug(url)

    # -- infrastructure -------------------------------------------------

    @property
    def files(self):
        return self.front.files

    def set_swarm(self, swarm, join_options=None) -> None:
        """Attach a peer swarm. `join_options` sets the repo's swarm
        posture (net/swarm.JoinOptions — announce and/or lookup;
        reference src/Repo.ts:20 setSwarm(swarm, joinOptions))."""
        self.back.set_swarm(swarm, join_options)

    def start_file_server(self, path: str) -> None:
        self.back.start_file_server(path)

    def close(self) -> None:
        self.back.close()
